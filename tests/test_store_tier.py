"""Elastic tiered store: mmap'd per-sizeclass spill slabs, checksummed
promotion, analytics-driven demotion, disk fault injection, and the
warm-restart walk.

Units drive ``DiskTier``/``Store`` directly (injected clocks, no
sockets); the live half boots python store subprocesses with a spill
tier and proves the two chaos contracts: a failing disk degrades the
hierarchy to DRAM-only (never a failed request), and a kill -9 +
restart on the same spill path boots a WARM cache whose persisted
prefixes serve store hits again without recompute."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from infinistore_tpu import protocol as P
from infinistore_tpu.store import (
    DISK_DEGRADE_AFTER,
    DISK_DOA_MIN_SAMPLES,
    DiskTier,
    MANIFEST_NAME,
)
from infinistore_tpu.utils import checksum as _checksum

from test_store_unit import make_store, make_tiered_store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BLK = 16 << 10


# ---------------------------------------------------------------------------
# DiskTier units
# ---------------------------------------------------------------------------


def test_slab_per_sizeclass_files_and_roundtrip(tmp_path):
    """Entries land in one mmap'd slab per power-of-two sizeclass and
    read back byte-identical (checksum-verified)."""
    t = DiskTier(str(tmp_path), 1 << 20, 4096)
    payloads = {
        b"a": b"x" * 4096,          # class 4096
        b"b": b"y" * 5000,          # class 8192
        b"c": b"z" * (12 << 10),    # class 16384
        b"d": b"w" * 100,           # class 4096 (sub-block payload)
    }
    for k, v in payloads.items():
        assert t.put(k, v)
    for k, v in payloads.items():
        assert t.get(k) == v
    files = sorted(f for f in os.listdir(tmp_path) if f.endswith(".dat"))
    assert files == ["spill_16384.dat", "spill_4096.dat", "spill_8192.dat"]
    rep = t.report()
    assert rep["entries"] == 4 and rep["verify_failures"] == 0
    assert set(rep["sizeclasses"]) == {"4096", "8192", "16384"}
    # slot reuse: pop then put the same class reuses the freed slot
    t.pop(b"a")
    assert t.put(b"a2", b"q" * 4096)
    assert t.report()["sizeclasses"]["4096"]["used"] == 2
    t.close()


def test_capacity_drops_oldest_for_good(tmp_path):
    """At capacity the tier drops its coldest entries — the reference
    hierarchy's behavior at the bottom of the stack."""
    t = DiskTier(str(tmp_path), 4 * 4096, 4096)
    for i in range(6):
        assert t.put(f"k{i}".encode(), bytes([i]) * 4096)
    assert len(t) == 4 and t.dropped == 2
    assert t.get(b"k0") is None and t.get(b"k1") is None
    assert t.get(b"k5") == bytes([5]) * 4096
    t.close()


def test_manifest_warm_boot_roundtrip(tmp_path):
    """close() persists the manifest; a fresh DiskTier on the same path
    boots with the index intact and every payload verified."""
    t = DiskTier(str(tmp_path), 1 << 20, 4096)
    data = {f"warm{i}".encode(): bytes([i + 1]) * (4096 + 128 * i)
            for i in range(8)}
    for k, v in data.items():
        assert t.put(k, v)
    t.close()
    assert os.path.exists(tmp_path / MANIFEST_NAME)

    t2 = DiskTier(str(tmp_path), 1 << 20, 4096)
    assert t2.warm_entries == 8 and len(t2) == 8
    for k, v in data.items():
        assert k in t2
        assert t2.get(k) == v
    assert t2.verify_failures == 0
    t2.close()


def test_orphan_spill_files_reaped_at_boot(tmp_path):
    """Spill files the manifest does not vouch for are unlinked at boot:
    leftovers of a crashed demotion, a geometry change, or an alien
    run must never sit on disk forever."""
    t = DiskTier(str(tmp_path), 1 << 20, 4096)
    t.put(b"keep", b"k" * 4096)
    t.close()
    # an orphan slab (never in the manifest) and a stray tmp
    (tmp_path / "spill_999424.dat").write_bytes(b"\0" * 4096)
    t2 = DiskTier(str(tmp_path), 1 << 20, 4096)
    assert t2.orphans_reaped == 1
    assert not os.path.exists(tmp_path / "spill_999424.dat")
    assert t2.get(b"keep") == b"k" * 4096  # the vouched slab survived
    t2.close()
    # geometry change (block_size): EVERYTHING is an orphan — cold boot
    t3 = DiskTier(str(tmp_path), 1 << 20, 8192)
    assert len(t3) == 0 and t3.orphans_reaped >= 1
    t3.close()


def test_corrupt_spill_page_caught_on_promote(tmp_path):
    """A flipped byte in a slab is caught by the per-record checksum at
    promote: the record is dropped (a counted miss), the sink fires,
    and the entry never serves bad bytes."""
    t = DiskTier(str(tmp_path), 1 << 20, 4096)
    seen = []
    t.corrupt_sink = seen.append
    t.put(b"good", b"g" * 4096)
    t.put(b"bad", b"b" * 4096)
    rec = t.index[b"bad"]
    path = os.path.join(str(tmp_path), f"spill_{rec.cls}.dat")
    with open(path, "r+b") as f:
        f.seek(rec.slot * rec.cls)
        f.write(b"\xff")
    assert t.get(b"bad") is None            # quarantined, not served
    assert t.verify_failures == 1 and seen == [b"bad"]
    assert b"bad" not in t                  # record gone for good
    assert t.get(b"good") == b"g" * 4096    # neighbors unaffected
    t.close()


def test_disk_error_degrades_tier_to_dram_only(tmp_path):
    """Consecutive I/O failures (the ``disk_error`` fault's shape)
    degrade the tier for a cooldown: puts/gets answer DRAM-only
    immediately instead of paying the error every access; the cooldown
    ends and the tier recovers."""
    clock = [0.0]
    t = DiskTier(str(tmp_path), 1 << 20, 4096, clock=lambda: clock[0])
    t.put(b"pre", b"p" * 4096)
    boom = [True]

    def fault(kind):
        if boom[0]:
            raise OSError(28, "injected ENOSPC")

    t.fault = fault
    for i in range(DISK_DEGRADE_AFTER):
        assert not t.put(f"f{i}".encode(), b"x" * 4096)
    assert t.io_errors == DISK_DEGRADE_AFTER and t.degraded()
    # degraded: presence and reads answer DRAM-only (miss), no I/O paid
    assert b"pre" not in t and t.get(b"pre") is None
    assert not t.put(b"later", b"y" * 4096)
    # cooldown over + disk healthy again: full service resumes
    boom[0] = False
    clock[0] += 1e6
    assert not t.degraded()
    assert t.get(b"pre") == b"p" * 4096
    assert t.put(b"later", b"y" * 4096)
    t.close()


# ---------------------------------------------------------------------------
# Store-level: demotion, DOA admission gate, disk-full mid-demotion
# ---------------------------------------------------------------------------


def _clocked_tiered_store(tmp_path):
    s = make_tiered_store(tmp_path)
    clock = [100.0]
    s._clock = lambda: clock[0]
    s.disk._clock = s._clock
    return s, clock


def test_demote_step_moves_cold_entries_off_dram(tmp_path):
    """The background demotion pass: cold committed entries (age beyond
    the band threshold, pool above the watermark) move to disk and free
    their DRAM; young entries stay; access promotes back (verified)."""
    s, clock = _clocked_tiered_store(tmp_path)
    s.demote_after_s = 10.0
    s.demote_watermark = 0.1
    for i in range(16):
        assert s.put_inline(f"c{i}".encode(), bytes([i + 1]) * BLK) == P.FINISH
    clock[0] += 30.0  # everyone is cold now
    # touch the last four: they become young again (MRU + fresh stamp)
    for i in range(12, 16):
        s.get_inline(f"c{i}".encode())
    before_usage = s.mm.usage()
    moved = 0
    while True:
        n = s.demote_step(max_entries=4)
        if n == 0:
            break
        moved += n
    assert moved == 12, moved                 # only the cold 12
    assert s.stats.demoted == 12
    assert s.mm.usage() < before_usage        # DRAM actually freed
    for i in range(12):
        assert s.exist(f"c{i}".encode())      # still present via disk
        assert f"c{i}".encode() in s.disk.index
    # promotion on access, byte-identical
    assert bytes(s.get_inline(b"c3")) == bytes([4]) * BLK
    assert s.stats.promoted == 1 and b"c3" not in s.disk.index
    s.close()


def test_demote_respects_watermark_and_age(tmp_path):
    s, clock = _clocked_tiered_store(tmp_path)
    s.demote_after_s = 10.0
    s.demote_watermark = 0.9  # pool far below: nothing to make room for
    for i in range(8):
        s.put_inline(f"w{i}".encode(), b"x" * BLK)
    clock[0] += 30.0
    assert s.demote_step() == 0
    s.demote_watermark = 0.0
    clock[0] -= 25.0  # entries now younger than demote_after_s
    assert s.demote_step() == 0
    s.close()


def test_doa_gate_refuses_never_read_entries(tmp_path):
    """Disk admission is gated by the eviction attribution: once the
    record says most writes are dead on arrival, never-read entries are
    refused (spilling them just moves the waste to disk I/O) while
    read entries still earn their slot."""
    s, _clock = _clocked_tiered_store(tmp_path)
    s.analytics.dead_on_arrival = DISK_DOA_MIN_SAMPLES
    s.analytics.evicted_read = 0
    s.put_inline(b"never-read", b"n" * BLK)
    s.put_inline(b"was-read", b"r" * BLK)
    s.get_inline(b"was-read")
    assert not s._disk_admit(s.kv[b"never-read"])
    assert s._disk_admit(s.kv[b"was-read"])
    for e in s.kv.values():
        e.lease = 0
    s.evict(0.0, 0.0)
    assert b"was-read" in s.disk.index
    assert b"never-read" not in s.disk.index
    # with a healthy read ratio the gate admits everyone again
    s.analytics.evicted_read = DISK_DOA_MIN_SAMPLES * 9
    s.put_inline(b"fresh", b"f" * BLK)
    assert s._disk_admit(s.kv[b"fresh"])
    s.close()


def test_disk_full_mid_demotion_stops_pass_and_keeps_dram_copy(tmp_path):
    """ENOSPC mid-demotion: the pass stops, the entry KEEPS its DRAM
    copy (a failed demotion must lose nothing), the error is counted,
    and enough failures degrade the tier."""
    s, clock = _clocked_tiered_store(tmp_path)
    s.demote_after_s = 1.0
    s.demote_watermark = 0.0
    for i in range(6):
        s.put_inline(f"d{i}".encode(), b"x" * BLK)
    clock[0] += 10.0
    fails = [0]

    def fault(kind):
        if kind == "write":
            fails[0] += 1
            raise OSError(28, "injected ENOSPC")

    s.disk.fault = fault
    assert s.demote_step(max_entries=4) == 0
    assert fails[0] == 1 and s.disk.io_errors == 1
    assert len(s.kv) == 6           # nothing left DRAM
    assert len(s.disk.index) == 0   # nothing half-written is indexed
    # keep failing: the tier degrades and demote_step short-circuits
    for _ in range(DISK_DEGRADE_AFTER):
        s.demote_step(max_entries=1)
    assert s.disk.degraded()
    assert s.demote_step() == 0 and fails[0] <= DISK_DEGRADE_AFTER + 1
    s.close()


def test_demote_all_then_warm_boot_sees_everything(tmp_path):
    """The graceful pre-restart drain: demote_all moves every committed
    entry + saves the manifest; a rebuilt store on the same path
    answers presence and promotes byte-identical payloads."""
    s, _clock = _clocked_tiered_store(tmp_path)
    data = {f"p{i}".encode(): bytes([i + 1]) * BLK for i in range(10)}
    for k, v in data.items():
        s.put_inline(k, v)
    assert s.demote_all() == 10
    assert s.kvmap_len() == 0 and len(s.disk.index) == 10
    s.close()

    s2 = make_store()
    s2.disk = DiskTier(str(tmp_path), 64 * BLK, BLK)
    assert s2.disk.warm_entries == 10
    keys = sorted(data)
    assert s2.match_last_index(keys + [b"absent"]) == len(keys) - 1
    for k, v in data.items():
        assert bytes(s2.get_inline(k)) == v
    assert s2.stats.promoted == 10
    s2.close()


def test_list_keys_spans_both_tiers(tmp_path):
    s, _clock = _clocked_tiered_store(tmp_path)
    for i in range(4):
        s.put_inline(f"dram{i}".encode(), b"a" * BLK)
    s.put_inline(b"cold", b"c" * BLK)
    s.demote_all()
    for i in range(4):
        s.put_inline(f"dram{i}".encode(), b"a" * BLK)
    keys = set(s.list_keys())
    assert keys == {"dram0", "dram1", "dram2", "dram3", "cold"}
    assert s.list_keys(limit=2) and len(s.list_keys(limit=2)) == 2
    s.close()


def test_console_spill_row():
    """istpu-top's spill row (per the established Console.frame fixture
    pattern): occupancy bar, per-frame demote/promote deltas, and the
    degraded shout."""
    from infinistore_tpu.top import Console, Snapshot

    disk = {
        "entries": 42, "bytes": 42 << 14, "slot_bytes": 48 << 14,
        "capacity_bytes": 96 << 14, "spilled": 30, "demoted": 12,
        "promoted": 7, "dropped": 0, "io_errors": 0,
        "verify_failures": 0, "orphans_reaped": 0, "warm_entries": 20,
        "degraded": False, "sizeclasses": {"16384": {"slots": 48,
                                                     "used": 42}},
    }
    cache = {"entries": 10, "hits": 5, "misses": 1, "evicted": 30,
             "mean_reuse_s": 0.5, "disk": disk}
    console = Console()
    frame1 = console.frame(Snapshot(cache=cache))
    assert "spill tier" in frame1 and "entries      42" in frame1
    assert "warm 20" in frame1
    # second frame: +3 demotions, +2 promotions since the last poll
    cache2 = json.loads(json.dumps(cache))
    cache2["disk"]["demoted"] = 15
    cache2["disk"]["promoted"] = 9
    frame2 = console.frame(Snapshot(cache=cache2))
    assert "demote +3 /frame" in frame2 and "promote +2 /frame" in frame2
    # degraded + errors shout
    cache2["disk"]["degraded"] = True
    cache2["disk"]["io_errors"] = 4
    cache2["disk"]["verify_failures"] = 1
    frame3 = console.frame(Snapshot(cache=cache2))
    assert "DEGRADED (DRAM-only)" in frame3
    assert "io-errors 4" in frame3 and "corrupt 1" in frame3


# ---------------------------------------------------------------------------
# live: disk chaos + THE warm-restart walk
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _boot_tiered(port, mport, tier_dir, extra_env=None):
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", "python",
         "--disk-tier-path", tier_dir, "--disk-tier-size", "1"],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "ISTPU_DISK_COOLDOWN_S": "1", **(extra_env or {})},
    )
    deadline = time.time() + 30
    for p in (port, mport):
        while True:
            if proc.poll() is not None:
                pytest.fail("tiered store failed to start")
            try:
                socket.create_connection(("127.0.0.1", p),
                                         timeout=0.5).close()
                break
            except OSError:
                if time.time() >= deadline:
                    proc.kill()
                    pytest.fail(f"store port {p} did not come up")
                time.sleep(0.1)
    return proc


def _mget(mport, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{mport}{path}",
                                timeout=10) as r:
        return json.loads(r.read())


def _mpost(mport, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{mport}{path}",
        data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_live_disk_error_chaos_degrades_to_dram_only(tmp_path):
    """THE disk chaos contract: arm ``disk_error`` → spill/promote I/O
    fails → the tier degrades to DRAM-only — every client op still
    answers (a read of a lost entry is a clean KeyNotFound miss, never
    a hang or a 500-shaped error), faults and io_errors are counted —
    then clear + cooldown → the tier serves again."""
    import numpy as np

    import infinistore_tpu as ist

    port, mport = _free_port(), _free_port()
    proc = _boot_tiered(port, mport, str(tmp_path))
    try:
        cfg = ist.ClientConfig(host_addr="127.0.0.1", service_port=port,
                               connection_type=ist.TYPE_TCP,
                               log_level="warning", op_timeout_s=15)
        conn = ist.InfinityConnection(cfg)
        conn.connect()
        n = 8
        buf = np.random.RandomState(3).randint(
            0, 256, size=n * BLK, dtype=np.uint8)
        conn.register_mr(buf)
        keys = [f"chaos-{i}" for i in range(n)]
        conn.write_cache([(k, i * BLK) for i, k in enumerate(keys)],
                         BLK, buf.ctypes.data)
        # arm the fault FIRST (house rule: the failure mode exists
        # before its mitigation is exercised), then force eviction
        _mpost(mport, "/faults",
               [{"op": "DISK", "action": "disk_error"}])
        conn.evict(0.0, 0.0)
        stats = _mget(mport, "/stats")
        assert stats["kvmap_len"] == 0          # eviction proceeded
        assert stats["disk_entries"] == 0       # nothing spilled
        assert stats["disk_io_errors"] >= 1
        assert stats["disk_degraded"] == 1      # DRAM-only now
        # the data plane still answers: a lost entry is a CLEAN miss
        out = np.zeros(BLK, dtype=np.uint8)
        conn.register_mr(out)
        with pytest.raises(ist.InfiniStoreKeyNotFound):
            conn.read_cache([(keys[0], 0)], BLK, out.ctypes.data)
        # and fresh writes work (DRAM tier unaffected)
        conn.write_cache([("fresh", 0)], BLK, buf.ctypes.data)
        conn.read_cache([("fresh", 0)], BLK, out.ctypes.data)
        assert np.array_equal(out, buf[:BLK])
        mtext = urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/metrics", timeout=10).read().decode()
        assert 'istpu_store_faults_injected_total{op="DISK"' in mtext
        assert "istpu_store_disk_errors_total" in mtext
        # recovery: clear the fault, wait out the 1 s cooldown, evict
        # again — the tier spills again.  A NEVER-read key: the read
        # above left "fresh" under a GET_DESC lease the evictor skips.
        _mpost(mport, "/faults", [])
        time.sleep(1.2)
        conn.write_cache([("fresh2", 0)], BLK, buf.ctypes.data)
        conn.evict(0.0, 0.0)
        stats = _mget(mport, "/stats")
        assert stats["disk_degraded"] == 0
        assert stats["disk_entries"] >= 1
        conn.close()
    finally:
        proc.kill()
        proc.wait()


jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from infinistore_tpu.engine import InferenceEngine  # noqa: E402
from infinistore_tpu.kv import PagedCacheConfig  # noqa: E402
from infinistore_tpu.models import TINY, init_params, scaled  # noqa: E402
from infinistore_tpu.utils import metrics as m  # noqa: E402

CFG = scaled(TINY, dtype=jnp.float32)
PARAMS = init_params(CFG, jax.random.PRNGKey(7))
T = 4
PROMPT = [11, 42, 7, 99, 5, 3, 17, 28, 64, 1, 2]


def _pc(n_blocks=64):
    return PagedCacheConfig(
        n_layers=CFG.n_layers, n_kv_heads=CFG.n_kv_heads,
        head_dim=CFG.head_dim, n_blocks=n_blocks, block_tokens=T,
        dtype=CFG.dtype,
    )


def _engine(port, **kw):
    import infinistore_tpu as ist

    conn = ist.InfinityConnection(ist.ClientConfig(
        host_addr="127.0.0.1", service_port=port,
        connection_type=ist.TYPE_TCP, log_level="warning",
        op_timeout_s=15,
    ))
    conn.connect()
    kw.setdefault("kv_quant", None)
    return InferenceEngine(PARAMS, CFG, _pc(), conn=conn,
                           model_id="tier-serve", **kw)


def _epoch_fences():
    return m.default_registry().family_value(
        "istpu_integrity_failures_total", where={"cause": "epoch"}) or 0.0


def test_warm_restart_serves_persisted_prefixes_without_recompute(tmp_path):
    """THE warm-restart chaos walk (acceptance): push a prefix → POST
    /spill (graceful demote-all) → SIGKILL → restart on the same port
    and spill path → the epoch fence counts on reconnect → the SAME
    prefix serves a STORE hit (promoted off disk, checksum-verified)
    with zero recompute — the store survived the deploy as a warm
    cache."""
    port, mport = _free_port(), _free_port()
    proc = _boot_tiered(port, mport, str(tmp_path))
    try:
        producer = _engine(port)
        st = producer.prefill(PROMPT)
        producer.release(st)
        producer.store_flush()
        demoted = _mpost(mport, "/spill", {})
        assert demoted["demoted"] > 0
        stats = _mget(mport, "/stats")
        assert stats["disk_entries"] > 0

        # hard death + restart on the SAME port and spill path
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        proc = _boot_tiered(port, mport, str(tmp_path))
        stats = _mget(mport, "/stats")
        assert stats["disk_warm_entries"] > 0   # booted WARM
        assert stats["kvmap_len"] == 0          # nothing recomputed yet

        # the PRODUCER's long-lived connection reconnects across the
        # restart: its next op finds the socket dead, reconnects, and
        # the new HELLO's epoch differs → fence counted (the client
        # remap the warm restart relies on).  A brand-new connection
        # has no old epoch to fence against, which is why the fence is
        # asserted on the survivor, not the fresh consumer.
        before_fence = _epoch_fences()
        assert producer.transfer._call("check_exist", "remap-probe") == 1
        assert _epoch_fences() > before_fence, \
            "reconnect across the restart must count an epoch fence"
        # a FRESH engine (no local prefix cache): its prefill finds the
        # whole persisted prefix in the store tier and LOADS it — store
        # provenance, zero recompute of the persisted chunks
        consumer = _engine(port)
        st2 = consumer.prefill(PROMPT)
        complete = (len(PROMPT) - 1) // T  # reusable whole chunks
        assert st2.store_chunks == complete and st2.reused_chunks == complete
        assert st2.local_chunks == 0
        stats = _mget(mport, "/stats")
        assert stats["disk_promoted"] > 0       # pages came OFF DISK
        assert stats["disk_verify_failures"] == 0
        consumer.release(st2)
    finally:
        proc.kill()
        proc.wait()
