"""Distributed helpers on the virtual 8-device CPU mesh (single process)."""

import jax
import jax.numpy as jnp
import numpy as np

from infinistore_tpu.parallel.distributed import (
    dcn_aware_store_targets,
    initialize,
    make_hybrid_mesh,
    process_local_batch,
)


def test_initialize_noop_single_process():
    initialize()  # no env configured -> must be a no-op, not a hang/raise


def test_hybrid_mesh_single_process():
    mesh = make_hybrid_mesh(tp=2)
    assert dict(mesh.shape) == {"dp": 4, "pp": 1, "sp": 1, "tp": 2}
    # the mesh is usable: a psum over dp x tp sees all 8 devices
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(
        jnp.arange(8.0).reshape(4, 2), NamedSharding(mesh, P("dp", "tp"))
    )
    total = jax.jit(lambda v: v.sum())(x)
    assert float(total) == 28.0


def test_process_local_batch_and_targets():
    assert process_local_batch(32) == 32  # single process
    hosts = ["10.0.0.1", "10.0.0.2"]
    assert dcn_aware_store_targets(hosts, my_rank=0) == "10.0.0.1"
    assert dcn_aware_store_targets(hosts, my_rank=3) == "10.0.0.2"
