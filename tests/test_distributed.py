"""Distributed helpers on the virtual 8-device CPU mesh (single process)."""

import jax
import jax.numpy as jnp
import numpy as np

from infinistore_tpu.parallel.distributed import (
    dcn_aware_store_targets,
    initialize,
    make_hybrid_mesh,
    process_local_batch,
)


def test_initialize_noop_single_process():
    initialize()  # no env configured -> must be a no-op, not a hang/raise


def test_hybrid_mesh_single_process():
    mesh = make_hybrid_mesh(tp=2)
    assert dict(mesh.shape) == {"dp": 4, "pp": 1, "sp": 1, "tp": 2}
    # the mesh is usable: a psum over dp x tp sees all 8 devices
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(
        jnp.arange(8.0).reshape(4, 2), NamedSharding(mesh, P("dp", "tp"))
    )
    total = jax.jit(lambda v: v.sum())(x)
    assert float(total) == 28.0


def test_hybrid_mesh_dcn_groups_single_process():
    """dcn_dp > 1 on virtual devices: the slice_index-less fallback builds
    the same mesh SHAPE as the real hybrid path (dp outermost over DCN),
    and it actually executes a partitioned computation."""
    mesh = make_hybrid_mesh(dcn_dp=2, tp=2)
    assert dict(mesh.shape) == {"dp": 4, "pp": 1, "sp": 1, "tp": 2}
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(
        jnp.arange(16.0).reshape(4, 4),
        NamedSharding(mesh, P("dp", "tp")),
    )
    assert float(jax.jit(lambda v: v.sum())(x)) == 120.0


def test_hybrid_mesh_engine_decode():
    """Multi-host serving shape: the engine's GSPMD decode runs over a
    hybrid ICIxDCN mesh (dp across the virtual DCN axis, tp inside) and
    matches the single-device engine (VERDICT r3 next #8; the full
    store-mediated two-host flow runs in __graft_entry__.dryrun)."""
    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models import TINY, init_params

    params = init_params(TINY, jax.random.PRNGKey(2))
    pc = PagedCacheConfig(
        n_layers=TINY.n_layers, n_kv_heads=TINY.n_kv_heads,
        head_dim=TINY.head_dim, n_blocks=16, block_tokens=4,
    )
    prompt = [1, 2, 3, 4, 5, 6, 7]
    ref = InferenceEngine(params, TINY, pc)
    want = ref.decode(ref.prefill(prompt), 4)

    mesh = make_hybrid_mesh(dcn_dp=2, tp=2)
    with jax.set_mesh(mesh):
        eng = InferenceEngine(params, TINY, pc, mesh=mesh)
        got = eng.decode(eng.prefill(prompt), 4)
    assert got == want


def test_process_local_batch_and_targets():
    assert process_local_batch(32) == 32  # single process
    hosts = ["10.0.0.1", "10.0.0.2"]
    assert dcn_aware_store_targets(hosts, my_rank=0) == "10.0.0.1"
    assert dcn_aware_store_targets(hosts, my_rank=3) == "10.0.0.2"
