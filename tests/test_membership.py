"""Live ring membership: join/drain with background migration under
traffic, the per-request streamer flush marker, and THE 3→4→3 node walk.

The unit half drives ``RoutedStorePool`` membership over fake in-memory
connections (migration routing is pure bookkeeping + two wire verbs);
the live half runs a serving server over real store subprocesses, walks
the fleet 3→4→3 through ``POST /debug/cluster`` WHILE an open-loop
loadgen flood runs, and asserts zero failed requests with store-hit
provenance recovering after each transition — ROADMAP item 4's
acceptance."""

import ctypes
import http.client
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from infinistore_tpu.cluster import HashRing, RoutedStorePool
from infinistore_tpu.utils import metrics as m

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# membership units over fake connections
# ---------------------------------------------------------------------------


STORES = {}


class FakeConn:
    """The four verbs migration needs, over an in-memory dict per
    endpoint — mimics the public ``InfinityConnection`` surface the
    pool's nodes hold."""

    def __init__(self, ep):
        self.ep = ep

    def connect(self):
        if STORES.get(self.ep) is None:
            raise ConnectionError(f"{self.ep} unreachable")

    def close(self):
        pass

    def list_keys(self, limit=0):
        return list(STORES[self.ep])

    def check_exist(self, key):
        return key in STORES[self.ep]

    def tcp_read_cache(self, key):
        from infinistore_tpu.lib import InfiniStoreKeyNotFound

        if key not in STORES[self.ep]:
            raise InfiniStoreKeyNotFound(key)
        return np.frombuffer(STORES[self.ep][key], dtype=np.uint8).copy()

    def tcp_write_cache(self, key, ptr, size):
        STORES[self.ep][key] = bytes(
            (ctypes.c_ubyte * size).from_address(ptr))


def _fake_pool(n=3, **kw):
    eps = [f"10.9.0.{i}:5000" for i in range(1, n + 1)]
    for ep in eps:
        STORES[ep] = {}
    return RoutedStorePool(eps, conn_factory=FakeConn, **kw), eps


def _seed(pool, n=200):
    keys = [f"mem:k{i}#L0" for i in range(n)]
    for k in keys:
        STORES[pool.ring.owner(k)][k] = f"payload-{k}".encode()
    return keys


def _wait_idle(pool, timeout=10.0):
    deadline = time.time() + timeout
    while not pool.migration_idle():
        assert time.time() < deadline, "migration did not finish"
        time.sleep(0.02)


def test_join_migrates_exactly_the_new_nodes_range():
    pool, eps = _fake_pool()
    keys = _seed(pool)
    old_ring = pool.ring.clone()
    new_ep = "10.9.0.9:5000"
    STORES[new_ep] = {}
    pool.join_node(new_ep)
    _wait_idle(pool)
    rep = pool.migration_report()
    assert rep["state"] == "done" and rep["mode"] == "join"
    assert rep["errors"] == 0
    moved = [k for k in keys if pool.ring.owner(k) == new_ep]
    assert moved, "a joined node must own a share"
    # exactly the ~1/N range: every key the new ring assigns it arrived,
    # and nothing else did
    assert set(STORES[new_ep]) == set(moved)
    assert rep["copied"] == len(moved)
    # the consistent-hashing contract held: no key shuffled among the
    # OLD nodes
    for k in keys:
        if k not in moved:
            assert pool.ring.owner(k) == old_ring.owner(k)
    assert pool.membership(new_ep) == "active"
    pool.close()


def test_candidates_ride_old_owner_during_transition():
    """While a migration runs, the PRE-change owner rides the end of the
    candidate walk — reads stay correct before the copy lands."""
    pool, eps = _fake_pool()
    keys = _seed(pool, 50)
    new_ep = "10.9.0.9:5000"
    STORES[new_ep] = {}
    # stall the migrator so the transition window stays open
    real_copy = pool._copy_key
    gate = threading.Event()

    def slow_copy(key, src, dst):
        gate.wait(5)
        return real_copy(key, src, dst)

    pool._copy_key = slow_copy
    pool.join_node(new_ep)
    try:
        assert pool.membership(new_ep) == "joining"
        moved = [k for k in keys if pool.ring.owner(k) == new_ep]
        assert moved
        k = moved[0]
        cands = pool.candidates(k)
        assert cands[0] == new_ep
        old_owner = HashRing(eps, vnodes=pool.ring.vnodes).owner(k)
        assert old_owner in cands, \
            "migration reads must fail over to the pre-change owner"
        rep = pool.report()
        by_ep = {n["endpoint"]: n for n in rep["nodes"]}
        assert by_ep[new_ep]["membership"] == "joining"
        assert rep["migration"]["state"] == "running"
    finally:
        gate.set()
        _wait_idle(pool)
    # transition over: the old owner drops off the walk
    k = [k for k in keys if pool.ring.owner(k) == new_ep][0]
    assert len(pool.candidates(k)) == pool.replicas
    pool.close()


def test_drain_copies_range_out_then_forgets_the_node():
    pool, eps = _fake_pool()
    keys = _seed(pool)
    victim = eps[1]
    owned = [k for k in keys if pool.ring.owner(k) == victim]
    assert owned
    pool.drain_node(victim)
    assert pool.membership(victim) == "draining"
    # writes already exclude the draining node (it left the ring)
    for k in keys:
        assert victim not in pool.write_targets(k)
    _wait_idle(pool)
    rep = pool.migration_report()
    assert rep["state"] == "done" and rep["mode"] == "drain"
    assert rep["errors"] == 0
    assert victim not in pool.endpoints and victim not in pool._nodes
    # every key the victim owned is now retrievable from its new owner
    for k in owned:
        assert k in STORES[pool.ring.owner(k)]
    pool.close()


def test_one_membership_change_at_a_time():
    pool, eps = _fake_pool()
    _seed(pool, 500)
    real_copy = pool._copy_key
    gate = threading.Event()

    def slow_copy(key, src, dst):
        gate.wait(5)
        return real_copy(key, src, dst)

    pool._copy_key = slow_copy
    STORES["10.9.0.8:5000"] = {}
    STORES["10.9.0.9:5000"] = {}
    pool.join_node("10.9.0.8:5000")
    with pytest.raises(RuntimeError):
        pool.join_node("10.9.0.9:5000")
    with pytest.raises(RuntimeError):
        pool.drain_node(eps[0])
    gate.set()
    _wait_idle(pool)
    # and sanity rails: unknown drains / dup joins / last-node drains
    with pytest.raises(ValueError):
        pool.drain_node("10.9.9.9:1")
    with pytest.raises(ValueError):
        pool.join_node(eps[0])
    pool.close()


def test_join_refuses_unreachable_node():
    pool, eps = _fake_pool()
    STORES["10.9.0.7:5000"] = None  # FakeConn.connect raises
    with pytest.raises(RuntimeError):
        pool.join_node("10.9.0.7:5000")
    assert "10.9.0.7:5000" not in pool.endpoints
    assert pool.migration_idle()
    pool.close()


def test_console_cluster_membership_and_migration_rows():
    """istpu-top's cluster view shouts transition states and renders the
    live migration progress line."""
    from infinistore_tpu.top import Console, Snapshot

    cl = {
        "enabled": True, "replicas": 2, "vnodes": 64,
        "hot": {"hot_after": 3, "tracked": 2, "hot": 1, "pinned": 0},
        "replica_reads": {"hit": 0, "miss": 0},
        "migration": {"state": "running", "mode": "join",
                      "endpoint": "10.0.0.4:5000", "copied": 17,
                      "skipped": 2, "errors": 0, "total": 40},
        "nodes": [
            {"endpoint": "10.0.0.1:5000", "state": "closed",
             "membership": "active", "connected": True, "epoch": 1,
             "ownership": 0.4,
             "requests": {"ok": 10, "error": 0, "skipped": 0, "miss": 0}},
            {"endpoint": "10.0.0.4:5000", "state": "closed",
             "membership": "joining", "connected": True, "epoch": 2,
             "ownership": 0.2,
             "requests": {"ok": 1, "error": 0, "skipped": 0, "miss": 0}},
        ],
    }
    frame = Console().frame(Snapshot(cluster=cl))
    assert "JOINING" in frame
    assert "migration join 10.0.0.4:5000: 17/40 copied" in frame
    cl["nodes"][1]["membership"] = "draining"
    cl["migration"] = {"state": "done"}
    frame2 = Console().frame(Snapshot(cluster=cl))
    assert "DRAINING" in frame2 and "migration join" not in frame2


# ---------------------------------------------------------------------------
# live half: engines, serving, the walk
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _boot(port, mport):
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", "python"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    deadline = time.time() + 25
    for p in (port, mport):
        while True:
            if proc.poll() is not None:
                pytest.fail("store node failed to start")
            try:
                socket.create_connection(("127.0.0.1", p),
                                         timeout=0.5).close()
                break
            except OSError:
                if time.time() >= deadline:
                    proc.kill()
                    pytest.fail(f"store port {p} did not come up")
                time.sleep(0.1)
    return proc


jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from infinistore_tpu.engine import InferenceEngine  # noqa: E402
from infinistore_tpu.kv import PagedCacheConfig  # noqa: E402
from infinistore_tpu.kv.hashing import chunk_keys  # noqa: E402
from infinistore_tpu.models import TINY, init_params, scaled  # noqa: E402
from infinistore_tpu.serve import ServingServer  # noqa: E402

from conftest import make_dense_greedy  # noqa: E402

CFG = scaled(TINY, dtype=jnp.float32)
PARAMS = init_params(CFG, jax.random.PRNGKey(7))
T = 4
PROMPT = [11, 42, 7, 99, 5, 3, 17, 28, 64, 1, 2]
dense_greedy = make_dense_greedy(PARAMS, CFG)


def make_pc(n_blocks=128):
    return PagedCacheConfig(
        n_layers=CFG.n_layers, n_kv_heads=CFG.n_kv_heads,
        head_dim=CFG.head_dim, n_blocks=n_blocks, block_tokens=T,
        dtype=CFG.dtype,
    )


def _prompt(i):
    assert i < 450, i
    return [50 + i] + PROMPT[1:]


def _owned_prompt(ring, model_id, owner_ep, start=100):
    for i in range(start, 450):
        p = _prompt(i)
        keys = chunk_keys(p, model_id, chunk_tokens=T)
        if {ring.owner(k) for k in keys} == {owner_ep}:
            return p
    raise AssertionError("no prompt found with the wanted ownership")


def _post(port, body, timeout=180, path="/v1/completions"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data)


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


class _Fleet:
    def __init__(self, n=4):
        self.ports = [(_free_port(), _free_port()) for _ in range(n)]
        self.procs = [_boot(p, mp) for p, mp in self.ports]

    @property
    def endpoints(self):
        return [f"127.0.0.1:{p}" for p, _ in self.ports]

    def stop(self):
        for proc in self.procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


@pytest.fixture(scope="module")
def walk_fleet():
    f = _Fleet(4)  # three members + one spare to join
    yield f
    f.stop()


def test_three_four_three_walk_under_load(walk_fleet):
    """THE membership acceptance walk: a serving server over 3 store
    nodes, an open-loop flood running the whole time; join the 4th node
    (background migration) → store hits recover on the grown ring;
    drain it back out → store hits recover on the shrunk ring; ZERO
    failed requests end to end, all membership state read over HTTP."""
    from infinistore_tpu.loadgen import LoadConfig, run_load, summarize

    f = walk_fleet
    members, spare = f.endpoints[:3], f.endpoints[3]
    pool = RoutedStorePool(members, op_timeout_s=5.0, replicas=2)
    eng = InferenceEngine(
        PARAMS, CFG, make_pc(), conn=pool, model_id="walk-serve",
        store_durability="relaxed", kv_quant=None,
    )
    eng.decode_chunk = 4
    # this walk tests MEMBERSHIP routing under load, not the admission
    # plane: the CPU host's compile storms under the flood would trip
    # the burn shed into 429s and change what the walk observes (same
    # isolation rule PR 12 set for the health chaos fixture)
    prev_adm = os.environ.get("ISTPU_ADMISSION")
    os.environ["ISTPU_ADMISSION"] = "0"
    try:
        srv = ServingServer(eng, port=0, max_batch=4,
                            model_id="walk-serve")
    finally:
        if prev_adm is None:
            os.environ.pop("ISTPU_ADMISSION", None)
        else:
            os.environ["ISTPU_ADMISSION"] = prev_adm
    srv.start()
    prod_pools = []
    try:
        _post(srv.port, {"prompt": _prompt(0), "max_tokens": 4,
                         "temperature": 0})  # warm the compile caches

        def serve_metrics():
            st, data = _get(srv.port, "/metrics")
            assert st == 200
            return m.parse_prometheus_text(data.decode())

        def store_tokens():
            return serve_metrics().get(
                ("istpu_engine_prefix_tokens_total",
                 (("source", "store"),)), 0.0)

        def cluster_post(action, endpoint):
            return _post(srv.port, {"action": action,
                                    "endpoint": endpoint},
                        path="/debug/cluster")

        def wait_migration_done(deadline_s=60):
            deadline = time.time() + deadline_s
            while time.time() < deadline:
                st, data = _get(srv.port, "/debug/cluster")
                rep = json.loads(data)
                if rep["migration"].get("state") in ("done", "idle"):
                    return rep
                time.sleep(0.1)
            pytest.fail("migration did not finish")

        def seed_and_hit(endpoints, owner_ep, start):
            """Seed a store-only prefix owned by ``owner_ep`` via a
            FRESH producer pool on the CURRENT membership, then ask the
            serving stack: byte-exact tokens + a store-hit delta."""
            ring = HashRing(endpoints, vnodes=pool.ring.vnodes)
            p = _owned_prompt(ring, "walk-serve", owner_ep, start=start)
            prod_pool = RoutedStorePool(endpoints, op_timeout_s=5.0,
                                        replicas=1)
            prod_pools.append(prod_pool)
            prod = InferenceEngine(PARAMS, CFG, make_pc(64),
                                   conn=prod_pool, model_id="walk-serve",
                                   kv_quant=None)
            prod.release(prod.prefill(p))
            prod.store_flush()
            before = store_tokens()
            status, body = _post(srv.port, {
                "prompt": p, "max_tokens": 4, "temperature": 0})
            assert status == 200, body
            assert body["choices"][0]["token_ids"] == dense_greedy(p, 4)
            assert store_tokens() > before, \
                "store-hit provenance must recover after the transition"

        # open-loop flood across the WHOLE walk, in a thread
        load_out = {}

        def flood():
            results, makespan = run_load(
                f"http://127.0.0.1:{srv.port}", LoadConfig(
                    rate=3.0, n_requests=40, vocab=256, seed=5,
                    mix=((1.0, 11, 4),), timeout_s=120.0,
                    n_prefixes=2, prefix_len=8, prefix_frac=0.3,
                ))
            load_out["point"] = summarize(results, makespan, 60.0, 10.0,
                                          rate=3.0)

        flood_t = threading.Thread(target=flood, daemon=True)
        flood_t.start()
        time.sleep(0.5)  # the flood is live

        # ---- 3 → 4: join the spare under traffic ----
        status, rep = cluster_post("join", spare)
        assert status == 200, rep
        by_ep = {n["endpoint"]: n for n in rep["nodes"]}
        assert by_ep[spare]["membership"] in ("joining", "active")
        rep = wait_migration_done()
        assert len(rep["nodes"]) == 4
        assert all(n["membership"] == "active" for n in rep["nodes"])
        # membership rides /metrics and the health rollup too
        parsed = serve_metrics()
        assert parsed.get(("istpu_cluster_membership",
                           (("endpoint", spare),))) == 0.0
        st, data = _get(srv.port, "/debug/health")
        ring_view = json.loads(data)["cluster"]["ring"]
        assert {n["endpoint"] for n in ring_view} == set(f.endpoints)
        seed_and_hit(f.endpoints, spare, start=100)

        # ---- 4 → 3: drain it back out, still under traffic ----
        status, rep = cluster_post("drain", spare)
        assert status == 200, rep
        rep = wait_migration_done()
        assert {n["endpoint"] for n in rep["nodes"]} == set(members)
        seed_and_hit(members, members[0], start=250)

        flood_t.join(timeout=120)
        assert not flood_t.is_alive(), "flood did not drain"
        point = load_out["point"]
        # THE acceptance bar: zero failed requests across the 3→4→3 walk
        assert point["errors"] == 0 and point.get("rejected", 0) == 0, point
        assert point["completed"] == 40, point
    finally:
        srv.close()
        pool.close()
        for p in prod_pools:
            p.close()


# ---------------------------------------------------------------------------
# per-request flush marker (PR-13 handoff barrier follow-up)
# ---------------------------------------------------------------------------


def test_streamer_marker_flush_skips_other_requests():
    """Unit shape: a request's barrier waits for ITS pushes, not for
    another request's push still in flight."""
    from infinistore_tpu.engine.engine import _StoreStreamer
    from infinistore_tpu.utils import tracing

    class FakeBreaker:
        def allow(self):
            return True

        def record_success(self):
            pass

        def record_failure(self):
            pass

    class FakeTransfer:
        breaker = FakeBreaker()

        def push_begin(self, pages, keys):
            return ("tok", list(keys))

        def push_commit(self, token):
            if token[1][0].startswith("slow"):
                time.sleep(1.0)
            return 1

    st = _StoreStreamer(FakeTransfer(), maxsize=8, durability="relaxed")
    with tracing.TRACER.trace("req-B"):
        b = tracing.current_trace_id()
        st.submit(None, ["fast:1"])
    deadline = time.time() + 5
    while st._pending and time.time() < deadline:
        time.sleep(0.01)  # B's push lands
    with tracing.TRACER.trace("req-A"):
        a = tracing.current_trace_id()
        st.submit(None, ["slow:1"])  # worker busy ~1 s with A now
    time.sleep(0.05)
    t0 = time.perf_counter()
    st.flush(marker=b)
    dt_b = time.perf_counter() - t0
    t0 = time.perf_counter()
    st.flush(marker=a)
    dt_a = time.perf_counter() - t0
    assert dt_b < 0.3, f"B's barrier joined A's push ({dt_b:.2f}s)"
    assert dt_a > 0.3, dt_a
    st.flush()  # full join still clean


def test_streamer_marker_flush_surfaces_own_error():
    """A request whose pushes failed (or were skipped behind a parked
    error) must see the failure at ITS barrier — 'flushed: true' means
    durable."""
    from infinistore_tpu.engine.engine import _StoreStreamer
    from infinistore_tpu.utils import tracing

    class FakeBreaker:
        def allow(self):
            return True

        def record_success(self):
            pass

        def record_failure(self):
            pass

    class BoomTransfer:
        breaker = FakeBreaker()

        def push_begin(self, pages, keys):
            return ("tok", list(keys))

        def push_commit(self, token):
            raise RuntimeError("store died")

    st = _StoreStreamer(BoomTransfer(), maxsize=8, durability="relaxed")
    with tracing.TRACER.trace("req-X"):
        x = tracing.current_trace_id()
        st.submit(None, ["k1"])
    with pytest.raises(RuntimeError):
        st.flush(marker=x)
    # the parked state is NOT consumed by a marker flush: the full
    # flush (the idle join) still reports and clears it
    with pytest.raises(RuntimeError):
        st.flush()
    st.flush()


@pytest.fixture(scope="module")
def handoff_stack():
    """A serving server with a single-node store, relaxed durability,
    chunked prefill — the PD prefill-worker shape two concurrent
    ``POST /v1/prefill`` handoffs exercise."""
    import infinistore_tpu as ist

    port, mport = _free_port(), _free_port()
    proc = _boot(port, mport)
    conn = ist.InfinityConnection(ist.ClientConfig(
        host_addr="127.0.0.1", service_port=port,
        connection_type=ist.TYPE_TCP, log_level="warning",
        op_timeout_s=15,
    ))
    conn.connect()
    eng = InferenceEngine(
        PARAMS, CFG, make_pc(), conn=conn, model_id="handoff-serve",
        store_durability="relaxed", kv_quant=None, prefill_chunk=T,
    )
    # admission off: the deliberately-slowed pushes inflate TTFT far
    # past any SLO — the burn shed would 429 the very handoffs whose
    # barrier timing this fixture exists to measure
    prev_adm = os.environ.get("ISTPU_ADMISSION")
    os.environ["ISTPU_ADMISSION"] = "0"
    try:
        srv = ServingServer(eng, port=0, max_batch=4,
                            model_id="handoff-serve")
    finally:
        if prev_adm is None:
            os.environ.pop("ISTPU_ADMISSION", None)
        else:
            os.environ["ISTPU_ADMISSION"] = prev_adm
    srv.start()
    yield srv, eng
    srv.close()
    proc.terminate()
    proc.wait(timeout=10)


def test_concurrent_handoffs_no_cross_request_wait(handoff_stack):
    """THE regression (ROADMAP item 1b): two concurrent /v1/prefill
    handoffs — request A (short, fast pushes) must complete its flush
    barrier while request B's SLOW pushes are still draining.  The old
    whole-queue join made A wait for B's tail.  Patches push_commit
    (house rule: never push_pages)."""
    srv, eng = handoff_stack
    slow_prompt = [(7 * i) % 200 + 1 for i in range(24)]  # 5 complete chunks
    fast_prompt = [99, 3, 5, 7, 11, 13, 17, 19]           # 1 complete chunk
    slow_stems = set(chunk_keys(slow_prompt, "handoff-serve",
                                chunk_tokens=T))

    real_commit = eng.transfer.push_commit

    def gated_commit(token):
        if any(k in slow_stems for k in token[1]):
            time.sleep(0.7)
        return real_commit(token)

    eng.transfer.push_commit = gated_commit
    try:
        # warm both shapes first (compile storms must not pollute timing)
        _post(srv.port, {"prompt": [1] * 24, "max_tokens": 1,
                         "temperature": 0}, path="/v1/prefill")
        _post(srv.port, {"prompt": [1] * 8, "max_tokens": 1,
                         "temperature": 0}, path="/v1/prefill")

        done = {}

        def handoff(name, prompt):
            t0 = time.perf_counter()
            status, body = _post(srv.port, {
                "prompt": prompt, "max_tokens": 1, "temperature": 0,
            }, path="/v1/prefill")
            done[name] = (time.perf_counter() - t0, status, body)

        # A (fast) first: under the OLD whole-queue join its barrier
        # would absorb B's slow pushes arriving right behind it
        ta = threading.Thread(target=handoff,
                              args=("fast", fast_prompt))
        tb = threading.Thread(target=handoff,
                              args=("slow", slow_prompt))
        ta.start()
        time.sleep(0.05)
        tb.start()
        ta.join(timeout=60)
        tb.join(timeout=60)
        assert not ta.is_alive() and not tb.is_alive()
        fast_dt, fast_status, fast_body = done["fast"]
        slow_dt, slow_status, slow_body = done["slow"]
        assert fast_status == 200 and fast_body["flushed"], fast_body
        assert slow_status == 200 and slow_body["flushed"], slow_body
        # B's tail is ≥ 4 slow commits ≈ 2.8 s; A must NOT have waited
        # for it (old behavior: A's join ≈ B's, both > 2 s)
        assert slow_dt > 1.5, (slow_dt, fast_dt)
        assert fast_dt < slow_dt - 1.0, \
            f"fast handoff waited on slow pushes ({fast_dt:.2f}s " \
            f"vs {slow_dt:.2f}s)"
    finally:
        eng.transfer.push_commit = real_commit
        eng.store_flush()
