import os

import pytest

from infinistore_tpu.mempool import MM, Pool


@pytest.fixture
def pool():
    p = Pool("istpu_test_pool", 1 << 20, 4096)  # 256 blocks
    yield p
    p.close()


def test_basic_alloc_free(pool):
    off = pool.allocate(4096)
    assert off == 0
    off2 = pool.allocate(4096)
    assert off2 == 4096
    pool.deallocate(off, 4096)
    pool.deallocate(off2, 4096)
    assert pool.allocated_blocks == 0


def test_alloc_rounds_up_to_block(pool):
    off = pool.allocate(100)  # rounds up to one 4 KB block
    assert off is not None
    assert pool.allocated_blocks == 1
    pool.deallocate(off, 100)
    assert pool.allocated_blocks == 0


def test_multiblock_contiguous(pool):
    off = pool.allocate(4096 * 10)
    assert off is not None
    assert pool.allocated_blocks == 10
    pool.deallocate(off, 4096 * 10)


def test_exhaustion(pool):
    offs = [pool.allocate(4096) for _ in range(256)]
    assert all(o is not None for o in offs)
    assert pool.allocate(4096) is None
    pool.deallocate(offs[17], 4096)
    assert pool.allocate(4096) == offs[17]


def test_fragmentation_run_search(pool):
    offs = [pool.allocate(4096) for _ in range(256)]
    # free every other block: no run of 2 exists
    for i in range(0, 256, 2):
        pool.deallocate(offs[i], 4096)
    assert pool.allocate(8192) is None
    # free one neighbor: exactly one run of 2
    pool.deallocate(offs[1], 4096)
    assert pool.allocate(8192) == 0


def test_writes_visible_through_view(pool):
    off = pool.allocate(4096)
    pool.buf[off : off + 4] = b"abcd"
    assert bytes(pool.buf[off : off + 4]) == b"abcd"
    pool.deallocate(off, 4096)


def test_mm_multi_region_and_rollback():
    mm = MM(pool_size=1 << 20, block_size=4096)
    try:
        regions = mm.allocate(4096, 200)
        assert regions is not None and len(regions) == 200
        # not enough room for 100 more: all-or-nothing rollback
        before = mm.usage()
        assert mm.allocate(4096, 100) is None
        assert mm.need_extend
        assert mm.usage() == before
    finally:
        mm.close()


def test_mm_extend():
    mm = MM(pool_size=1 << 20, block_size=4096)
    try:
        assert mm.allocate(4096, 256) is not None
        assert mm.allocate(4096, 1) is None
        mm.add_mempool(1 << 20)
        regions = mm.allocate(4096, 1)
        assert regions == [(1, 0)]
        assert len(mm.pool_table()) == 2
    finally:
        mm.close()


def test_mm_usage():
    mm = MM(pool_size=1 << 20, block_size=4096)
    try:
        assert mm.usage() == 0.0
        mm.allocate(4096, 128)
        assert mm.usage() == pytest.approx(0.5)
    finally:
        mm.close()


def test_mm_allocate_contiguous_run():
    """Batch allocs come back as ONE run (region i at base + i*stride) so
    batch-put descriptors merge into bulk memcpys; per-entry deallocate
    frees exactly its own blocks."""
    mm = MM(pool_size=1 << 20, block_size=4096)
    try:
        regions = mm.allocate_contiguous(4096, 32)
        assert regions is not None and len(regions) == 32
        pis = {pi for pi, _ in regions}
        assert len(pis) == 1
        offs = [off for _, off in regions]
        assert offs == [offs[0] + i * 4096 for i in range(32)]
        # per-entry frees release only that entry's blocks
        for pi, off in regions[:16]:
            mm.deallocate(pi, off, 4096)
        assert mm.usage() == pytest.approx(16 * 4096 / (1 << 20))
        # sub-block sizes stride at the rounded-up block footprint
        r2 = mm.allocate_contiguous(100, 4)
        assert r2 is not None
        o2 = [off for _, off in r2]
        assert o2 == [o2[0] + i * 4096 for i in range(4)]
    finally:
        mm.close()


def test_mm_allocate_contiguous_fragmented_falls_back_to_none():
    """No run big enough -> None, WITHOUT setting need_extend (the store
    falls back to the per-region allocator, which still succeeds)."""
    mm = MM(pool_size=64 * 4096, block_size=4096)
    try:
        offs = [mm.allocate(4096, 1)[0] for _ in range(64)]
        for i in range(0, 64, 2):  # free every other block: no run of 2
            mm.deallocate(*offs[i], 4096)
        assert mm.allocate_contiguous(4096, 2) is None
        assert not mm.need_extend
        # the per-region path still places 2 regions in the holes
        assert mm.allocate(4096, 2) is not None
    finally:
        mm.close()


def test_mm_allocate_contiguous_sizeclass():
    """sizeclass mode: the run lives inside one class pool, striding at
    the class size; carving happens on demand."""
    mm = MM(pool_size=1 << 20, block_size=4096, allocator="sizeclass")
    try:
        regions = mm.allocate_contiguous(5000, 8)  # class 8192
        assert regions is not None
        offs = [off for _, off in regions]
        assert offs == [offs[0] + i * 8192 for i in range(8)]
        pi = regions[0][0]
        assert mm.pools[pi].block_size == 8192
        for _pi, off in regions:
            mm.deallocate(_pi, off, 5000)
        assert mm.pools[pi].allocated_blocks == 0
    finally:
        mm.close()


def test_find_run_doubling_matches_sequential(pool):
    """The O(log k) doubling run-finder must agree with first-fit for
    mixed run lengths under fragmentation."""
    offs = [pool.allocate(4096) for _ in range(256)]
    # carve holes of length 1, 3, 7 at known positions
    for i in (10, 20, 21, 22, 40, 41, 42, 43, 44, 45, 46):
        pool.deallocate(offs[i], 4096)
    pool._rover = 0
    assert pool.allocate(3 * 4096) == offs[20]   # first run of >=3
    assert pool.allocate(7 * 4096) == offs[40]
    assert pool.allocate(4096) == offs[10]
    assert pool.allocate(4096) is None


def test_sweep_stale_segments(tmp_path):
    import os

    from infinistore_tpu.mempool import sweep_stale_segments

    shm = str(tmp_path)
    dead = os.path.join(shm, "istpu_999999999_deadbeef_p0")
    open(dead, "wb").close()
    live = os.path.join(shm, f"istpu_{os.getpid()}_cafe_p0")
    open(live, "wb").close()
    other = os.path.join(shm, "not_ours")
    open(other, "wb").close()
    removed = sweep_stale_segments(shm)
    assert dead in removed and not os.path.exists(dead)
    assert os.path.exists(live) and os.path.exists(other)
    os.unlink(live)
    os.unlink(other)


def test_pool_creation_is_fast_and_prefaults_in_background():
    """bind/listen must not wait on pre-fault: creating a 256 MB pool
    returns quickly while pages populate on a background thread."""
    import time

    from infinistore_tpu.mempool import Pool

    t0 = time.monotonic()
    # pid in the name so sweep_stale_segments reclaims it if pytest dies
    p = Pool(f"istpu_{os.getpid()}_testfast{time.monotonic_ns()}", 256 << 20, 64 << 10)
    created_in = time.monotonic() - t0
    try:
        assert created_in < 2.0, created_in
        assert p.prefault_done.wait(timeout=30.0)
        # pool is usable while/after prefault
        off = p.allocate(64 << 10)
        p.buf[off : off + 4] = b"abcd"
        assert bytes(p.buf[off : off + 4]) == b"abcd"
    finally:
        p.close()


def test_sizeclass_classes_and_lazy_carving():
    """sizeclass MM: requests round to pow2 classes, each class carves
    its pool lazily, and mixed sizes never share a pool (the jemalloc-
    shaped option of reference design.rst:52)."""
    mm = MM(pool_size=1 << 20, block_size=4096, allocator="sizeclass")
    try:
        assert mm.pool_table() == []  # nothing carved yet
        a = mm.allocate(4096, 2)      # class 4096
        b = mm.allocate(5000, 2)      # rounds to class 8192
        c = mm.allocate(100, 1)       # below min -> class 4096
        assert a and b and c
        tbl = mm.pool_table()
        assert len(tbl) == 2          # one pool per touched class
        classes = sorted(bs for _, _, bs in tbl)
        assert classes == [4096, 8192]
        # same-class requests share a pool; cross-class never do
        assert {pi for pi, _ in a} == {pi for pi, _ in c}
        assert {pi for pi, _ in a}.isdisjoint({pi for pi, _ in b})
        # free and the blocks return to their class
        for pi, off in b:
            mm.deallocate(pi, off, 5000)
        b2 = mm.allocate(8000, 2)
        assert {pi for pi, _ in b2} == {pi for pi, _ in b}
    finally:
        mm.close()


def test_sizeclass_budget_and_extend():
    """The class pools carve from ONE budget; exhaustion sets
    need_extend, add_mempool grants budget (not a pool), and the retry
    carves the class that hit the wall."""
    mm = MM(pool_size=1 << 18, block_size=4096, allocator="sizeclass")
    try:
        # 64 blocks of 4 KB = the whole 256 KB budget
        assert mm.allocate(4096, 64) is not None
        assert mm.allocate(4096, 1) is None
        assert mm.need_extend
        assert mm.add_mempool(1 << 18) is None  # budget, not a pool
        mm.need_extend = False
        assert mm.allocate(4096, 1) is not None
        assert not mm.need_extend
    finally:
        mm.close()


def test_sizeclass_usage_counts_uncarved_budget():
    """usage() must count the uncarved budget as capacity — otherwise
    eviction thresholds would fire while whole classes remain unused."""
    mm = MM(pool_size=1 << 20, block_size=4096, allocator="sizeclass")
    try:
        regions = mm.allocate(4096, 16)  # 64 KB of a 1 MB budget
        assert regions is not None
        assert mm.usage() == pytest.approx(16 * 4096 / (1 << 20))
    finally:
        mm.close()


def test_sizeclass_large_class_does_not_swallow_budget():
    """A large first allocation must not carve the whole budget into its
    class: the carve chunk is budget/CARVE_DIVISOR (plus one-block
    minimum), so later classes still fit."""
    mm = MM(pool_size=1 << 20, block_size=4096, allocator="sizeclass")
    try:
        big = mm.allocate(100 << 10, 1)   # class 128 KB > budget/4
        assert big is not None
        small = mm.allocate(4096, 8)      # a different class must still fit
        assert small is not None
    finally:
        mm.close()


def test_sizeclass_rejects_absurd_sizes():
    mm = MM(pool_size=1 << 20, block_size=4096, allocator="sizeclass")
    try:
        assert mm.allocate(0, 1) is None
        assert mm.allocate((1 << 50) + 1, 1) is None  # no pow2 overflow path
    finally:
        mm.close()


def test_sizeclass_reclassifies_empty_pools_across_classes():
    """Carved budget never returns, so a fully-carved busy class must
    not starve the others forever: once its pools empty, a different
    class RECLASSIFIES the segments."""
    mm = MM(pool_size=1 << 18, block_size=4096, allocator="sizeclass")
    try:
        # carve the whole 256 KB budget into the 4 KB class
        a = mm.allocate(4096, 64)
        assert a is not None
        assert mm.allocate(8192, 1) is None  # no budget for a new class
        for pi, off in a:
            mm.deallocate(pi, off, 4096)
        b = mm.allocate(8192, 4)  # empty 4 KB pools reclassify to 8 KB
        assert b is not None
        classes = {bs for _, _, bs in mm.pool_table()}
        assert 8192 in classes
    finally:
        mm.close()


def test_sizeclass_eviction_could_satisfy_guard():
    """The store's pressure-evict loop must not run for requests no
    amount of eviction can satisfy."""
    mm = MM(pool_size=1 << 18, block_size=4096, allocator="sizeclass")
    try:
        assert mm.eviction_could_satisfy(4096, 1)
        assert mm.eviction_could_satisfy(4096, 64)
        assert not mm.eviction_could_satisfy(4096, 65)   # > whole budget
        assert not mm.eviction_could_satisfy(1 << 20, 1)  # class > budget
        assert not mm.eviction_could_satisfy(0, 1)
        assert not mm.eviction_could_satisfy((1 << 50) + 1, 1)
    finally:
        mm.close()


def test_sizeclass_reclassify_records_correct_pool_index():
    """REGRESSION (review r5): when a request is satisfied by
    RECLASSIFYING an empty pool, the recorded (pool_idx, offset) must
    point at THAT pool — recording the newest index sent view()/
    deallocate at the wrong pool's bytes (cross-class corruption)."""
    mm = MM(pool_size=1 << 18, block_size=4096, allocator="sizeclass")
    try:
        # carve pool 0 (4 KB class, 64 KB chunk) and pool 1 (8 KB class)
        a = mm.allocate(4096, 1)
        b = mm.allocate(8192, 1)
        assert a and b
        tbl = mm.pool_table()
        assert len(tbl) == 2
        # drain the 4 KB class; burn the REMAINING budget so the next
        # 16 KB class can only be served by reclassifying pool 0
        for pi, off in a:
            mm.deallocate(pi, off, 4096)
        filler = mm.allocate(4096, (1 << 18) // 4096)  # soak leftovers
        c = mm.allocate(16 << 10, 1)
        assert c is not None
        (pi, off) = c[0]
        # the reclassified pool is a REAL index whose block_size matches
        assert mm.pools[pi].block_size == 16 << 10
        # write/read through the recorded region: bytes must land in
        # that pool and never alias another pool's regions
        view = mm.view(pi, off, 16 << 10)
        view[:8] = b"REGRTEST"
        others = [bytes(mm.view(opi, ooff, 8)) for opi, ooff in (b or [])]
        del view  # release exported memoryviews before pool close
        assert all(o != b"REGRTEST" for o in others)
        mm.deallocate(pi, off, 16 << 10)
        assert mm.pools[pi].allocated_blocks == 0
    finally:
        mm.close()


def test_native_mempool_unit():
    """The C++ MM's unit checks (src/mempool_test.cpp): the mirrored
    carve-index-after-reclassify regression, size guards, and a bitmap
    round-trip — parity coverage the wire tests can't reach."""
    import subprocess

    binary = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src", "mempool_test")
    if not os.path.exists(binary):
        r = subprocess.run(
            ["make", "-C", os.path.dirname(binary), "mempool_test"],
            capture_output=True)
        assert r.returncode == 0, r.stderr.decode()[-500:]
    r = subprocess.run([binary], capture_output=True, timeout=60)
    assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode())
    assert b"OK" in r.stdout
