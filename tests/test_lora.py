"""Multi-LoRA serving: the punica-style batched-adapter path must match the
merged-weights oracle per adapter, mixed-adapter batches must work in one
lockstep dispatch, adapter KV must never cross adapters via prefix reuse,
and the HTTP front door must route "model": <adapter> requests.

Reference stack analog: vLLM multi-LoRA serving (SURVEY.md §2 row 25).
"""

import http.client
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu.engine import InferenceEngine, Scheduler
from infinistore_tpu.kv import PagedCacheConfig
from infinistore_tpu.models import TINY, init_params, scaled
from infinistore_tpu.models.lora import init_lora_bank, merge_lora

CFG = scaled(TINY, dtype=jnp.float32)
PARAMS = init_params(CFG, jax.random.PRNGKey(7))
# non-degenerate adapters (init_scale makes B nonzero so deltas matter)
BANK = init_lora_bank(
    CFG, ["ad-one", "ad-two"], rank=4, key=jax.random.PRNGKey(3),
    init_scale=0.5,
)
T = 4
PROMPT = [11, 42, 7, 99, 5, 3, 17, 28, 64, 1, 2]


def make_pc(n_blocks=64):
    return PagedCacheConfig(
        n_layers=CFG.n_layers, n_kv_heads=CFG.n_kv_heads,
        head_dim=CFG.head_dim, n_blocks=n_blocks, block_tokens=T,
        dtype=CFG.dtype,
    )


def merged_greedy(adapter_id, tokens, n):
    """Oracle: adapter folded into the base weights, plain engine."""
    eng = InferenceEngine(
        merge_lora(PARAMS, BANK, adapter_id), CFG, make_pc()
    )
    return eng.decode(eng.prefill(tokens), n)


def test_adapter_matches_merged_weights():
    """Batched-adapter decode == the merged-weights oracle, per adapter,
    and adapter 0 == the base model."""
    eng = InferenceEngine(PARAMS, CFG, make_pc(), lora=BANK)
    for aid in (0, 1, 2):
        st = eng.prefill(PROMPT, adapter_id=aid)
        got = eng.decode(st, 6)
        eng.release(st)
        assert got == merged_greedy(aid, PROMPT, 6), aid
    # the adapters genuinely differ (otherwise this file tests nothing)
    assert merged_greedy(1, PROMPT, 6) != merged_greedy(2, PROMPT, 6) or (
        merged_greedy(1, PROMPT, 6) != merged_greedy(0, PROMPT, 6)
    )


def test_mixed_adapter_lockstep_batch():
    """One decode_batch dispatch serves rows on different adapters."""
    eng = InferenceEngine(PARAMS, CFG, make_pc(), lora=BANK)
    sts = [eng.prefill(PROMPT, adapter_id=a) for a in (0, 1, 2)]
    outs = eng.decode_batch(sts, 6)
    for a, got in zip((0, 1, 2), outs):
        assert got == merged_greedy(a, PROMPT, 6), a


def test_scheduler_mixes_adapters():
    """Scheduler admission carries adapter ids end to end (wave prefill +
    lockstep decode)."""
    eng = InferenceEngine(PARAMS, CFG, make_pc(), lora=BANK)
    eng.decode_chunk = 4
    sched = Scheduler(eng, max_batch=4)
    a = sched.submit(PROMPT, 5, adapter_id=1)
    b = sched.submit(PROMPT[:7], 5, adapter_id=2)
    c = sched.submit(PROMPT[:5], 5)  # base
    out = sched.run()
    assert out[a] == merged_greedy(1, PROMPT, 5)
    assert out[b] == merged_greedy(2, PROMPT[:7], 5)
    assert out[c] == merged_greedy(0, PROMPT[:5], 5)


def test_adapter_prefix_isolation():
    """The same prompt under different adapters must NOT share KV pages:
    adapter KV is key-namespaced in the prefix cache."""
    eng = InferenceEngine(PARAMS, CFG, make_pc(), lora=BANK)
    st1 = eng.prefill(PROMPT, adapter_id=1)
    st2 = eng.prefill(PROMPT, adapter_id=2)
    assert st2.reused_chunks == 0  # no cross-adapter hit
    assert set(st1.chunk_keys).isdisjoint(st2.chunk_keys)
    # same adapter DOES reuse
    st3 = eng.prefill(PROMPT, adapter_id=1)
    assert st3.reused_chunks == len(PROMPT) // T
    out1 = eng.decode(st3, 4)
    assert out1 == merged_greedy(1, PROMPT, 4)  # reused pages are adapter-1 KV


def test_serve_routes_model_to_adapter():
    """HTTP: "model": <adapter name> routes to the adapter; /v1/models
    lists the base + adapters; unknown names 400."""
    from infinistore_tpu.serve import ServingServer

    eng = InferenceEngine(PARAMS, CFG, make_pc(), lora=BANK)
    eng.decode_chunk = 4
    srv = ServingServer(eng, port=0, max_batch=4, model_id="tiny-lora")
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=120)
        conn.request("GET", "/v1/models")
        cards = json.loads(conn.getresponse().read())["data"]
        assert [c["id"] for c in cards] == ["tiny-lora", "ad-one", "ad-two"]

        def post(body):
            conn.request("POST", "/v1/completions", json.dumps(body),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, json.loads(r.read())

        status, body = post({"prompt": PROMPT, "max_tokens": 5,
                             "temperature": 0, "model": "ad-one"})
        assert status == 200, body
        assert body["choices"][0]["token_ids"] == merged_greedy(1, PROMPT, 5)

        status, body = post({"prompt": PROMPT, "max_tokens": 5,
                             "temperature": 0, "model": "tiny-lora"})
        assert status == 200
        assert body["choices"][0]["token_ids"] == merged_greedy(0, PROMPT, 5)

        status, body = post({"prompt": PROMPT, "max_tokens": 2,
                             "model": "nope"})
        assert status == 400 and "nope" in body["error"]
        conn.close()
    finally:
        srv.close()
