"""Model correctness: paged decode must reproduce dense prefill exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu.kv import (
    BlockAllocator,
    PagedCacheConfig,
    init_cache,
    prefill_to_pages,
    write_pages,
)
from infinistore_tpu.models import (
    TINY,
    causal_attention,
    decode_forward,
    init_params,
    prefill_forward,
    scaled,
    train_step_fn,
)


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = scaled(TINY, dtype=jnp.float32)  # fp32 on CPU for exact comparisons
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_causal_attention_matches_naive():
    B, S, H, D = 2, 8, 4, 16
    key = jax.random.PRNGKey(1)
    q, k, v = (
        jax.random.normal(kk, (B, S, H, D), jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    out = causal_attention(q, k, v)
    # naive per-position reference
    for b in range(B):
        for i in range(S):
            logits = np.einsum("hd,khd->hk", q[b, i], k[b, : i + 1]) / np.sqrt(D)
            p = jax.nn.softmax(jnp.asarray(logits), axis=-1)
            ref = np.einsum("hk,khd->hd", p, v[b, : i + 1])
            np.testing.assert_allclose(out[b, i], ref, rtol=2e-5, atol=2e-5)


def test_prefill_shapes(tiny_setup):
    cfg, params = tiny_setup
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, cfg.vocab_size)
    logits, kv = jax.jit(lambda p, t: prefill_forward(p, cfg, t))(params, tokens)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert kv.shape == (cfg.n_layers, 2, 2, 32, cfg.n_kv_heads, cfg.head_dim)


def test_paged_decode_matches_prefill(tiny_setup):
    """Feed a sequence through prefill, then decode the last tokens one by one
    via the paged cache -- logits must match the dense forward."""
    cfg, params = tiny_setup
    T = 4  # block_tokens
    S_prefill, S_total = 8, 12
    B = 1
    tokens = jax.random.randint(jax.random.PRNGKey(3), (B, S_total), 0, cfg.vocab_size)

    # dense reference over the full sequence
    ref_logits, _ = prefill_forward(params, cfg, tokens)

    # paged: prefill first 8 tokens, page the kv, then decode tokens 8..11
    pc = PagedCacheConfig(
        n_layers=cfg.n_layers,
        n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim,
        n_blocks=16,
        block_tokens=T,
        dtype=cfg.dtype,
    )
    cache = init_cache(pc)
    alloc = BlockAllocator(pc.n_blocks)
    _, kv = prefill_forward(params, cfg, tokens[:, :S_prefill])
    n_pages = S_prefill // T
    pages = prefill_to_pages(kv[:, :, 0], n_pages, T)  # batch 0
    block_ids = alloc.alloc(n_pages + 1)  # one extra page for decode growth
    cache = write_pages(cache, jnp.asarray(block_ids[:n_pages]), pages)

    table = np.zeros((B, 4), dtype=np.int32)
    table[0, : n_pages + 1] = block_ids
    block_table = jnp.asarray(table)

    for pos in range(S_prefill, S_total):
        seq_lens = jnp.asarray([pos + 1], dtype=jnp.int32)
        slot_block = jnp.asarray([block_ids[pos // T]], dtype=jnp.int32)
        slot = jnp.asarray([pos % T], dtype=jnp.int32)
        logits, cache = decode_forward(
            params,
            cfg,
            tokens[:, pos],
            jnp.asarray([pos]),
            cache,
            block_table,
            seq_lens,
            slot_block,
            slot,
        )
        np.testing.assert_allclose(
            np.asarray(logits[0]),
            np.asarray(ref_logits[0, pos]),
            rtol=2e-4,
            atol=2e-4,
        )


def test_windowed_paged_decode_matches_prefill():
    """Sliding-window config: the paged decode mask must agree with the
    prefill mask.  Window (5) < prefilled length (8) so decode positions
    genuinely drop early keys, and a full-causal decode would diverge."""
    cfg = scaled(TINY, dtype=jnp.float32, sliding_window=5)
    params = init_params(cfg, jax.random.PRNGKey(7))
    T = 4
    S_prefill, S_total = 8, 12
    tokens = jax.random.randint(jax.random.PRNGKey(8), (1, S_total), 0, cfg.vocab_size)

    ref_logits, _ = prefill_forward(params, cfg, tokens)
    full_cfg = scaled(cfg, sliding_window=None)
    full_logits, _ = prefill_forward(params, full_cfg, tokens)
    assert not np.allclose(  # the window must actually bite
        np.asarray(ref_logits[0, -1]), np.asarray(full_logits[0, -1]),
        rtol=2e-4, atol=2e-4,
    )

    pc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, n_blocks=16, block_tokens=T, dtype=cfg.dtype,
    )
    cache = init_cache(pc)
    alloc = BlockAllocator(pc.n_blocks)
    _, kv = prefill_forward(params, cfg, tokens[:, :S_prefill])
    n_pages = S_prefill // T
    block_ids = alloc.alloc(n_pages + 1)
    cache = write_pages(
        cache, jnp.asarray(block_ids[:n_pages]),
        prefill_to_pages(kv[:, :, 0], n_pages, T),
    )
    table = np.zeros((1, 4), dtype=np.int32)
    table[0, : n_pages + 1] = block_ids
    for pos in range(S_prefill, S_total):
        logits, cache = decode_forward(
            params, cfg, tokens[:, pos], jnp.asarray([pos]), cache,
            jnp.asarray(table), jnp.asarray([pos + 1], dtype=jnp.int32),
            jnp.asarray([block_ids[pos // T]], dtype=jnp.int32),
            jnp.asarray([pos % T], dtype=jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(logits[0]), np.asarray(ref_logits[0, pos]),
            rtol=2e-4, atol=2e-4,
        )


def test_train_step_reduces_loss(tiny_setup):
    cfg, params = tiny_setup
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 16), 0, cfg.vocab_size)
    step = jax.jit(train_step_fn(cfg, lr=1e-2))
    _, loss0 = step(params, tokens)
    p, _ = step(params, tokens)
    for _ in range(5):
        p, loss = step(p, tokens)
    assert float(loss) < float(loss0)
