"""MoE model + expert parallelism vs single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from infinistore_tpu.models.moe import (
    TINY_MOE,
    init_moe_params,
    moe_loss_fn,
    moe_prefill_forward,
    moe_train_step_fn,
    scaled_moe,
    top_k_gates,
)
from infinistore_tpu.parallel.moe import (
    init_sharded_moe_params,
    make_moe_forward,
    make_moe_mesh,
    make_moe_train_step,
    moe_param_specs,
)
from infinistore_tpu.parallel.sharding import shardings_for

CFG = scaled_moe(TINY_MOE, dtype=jnp.float32)


def test_top_k_gates():
    logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0]])
    g = top_k_gates(logits, 2)
    assert g.shape == (1, 4)
    np.testing.assert_allclose(float(g.sum()), 1.0, rtol=1e-6)
    assert float(g[0, 2]) == 0.0 and float(g[0, 3]) == 0.0
    assert float(g[0, 0]) > float(g[0, 1]) > 0.0


def test_moe_forward_shapes_and_grad():
    params = init_moe_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    logits, kv = jax.jit(lambda p, t: moe_prefill_forward(p, CFG, t))(params, tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert kv.shape == (CFG.n_layers, 2, 2, 16, CFG.n_kv_heads, CFG.head_dim)
    step = jax.jit(moe_train_step_fn(CFG, lr=1e-2))
    p, loss0 = step(params, tokens)
    for _ in range(5):
        p, loss = step(p, tokens)
    assert float(loss) < float(loss0)


def test_expert_parallel_matches_dense():
    """ep-sharded forward/loss must equal the single-device dense MoE."""
    mesh = make_moe_mesh(dp=2, ep=4)
    params = init_moe_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, CFG.vocab_size)

    ref_logits, _ = moe_prefill_forward(params, CFG, tokens)
    ref_loss = moe_loss_fn(params, CFG, tokens)

    sharded = jax.device_put(params, shardings_for(mesh, moe_param_specs(CFG)))
    tok_sharded = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
    fwd = make_moe_forward(CFG, mesh)
    got = fwd(sharded, tok_sharded)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref_logits), rtol=2e-5, atol=2e-5
    )


def test_expert_parallel_train_matches_dense():
    mesh = make_moe_mesh(dp=2, ep=4)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, CFG.vocab_size)

    ref_params = init_moe_params(CFG, jax.random.PRNGKey(0))
    ref_step = jax.jit(moe_train_step_fn(CFG, lr=1e-2))

    ep_params = init_sharded_moe_params(CFG, mesh, jax.random.PRNGKey(0))
    ep_step = make_moe_train_step(CFG, mesh, lr=1e-2)
    tok_sharded = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))

    for i in range(3):
        ref_params, ref_loss = ref_step(ref_params, tokens)
        ep_params, ep_loss = ep_step(ep_params, tok_sharded)
        np.testing.assert_allclose(
            float(ep_loss), float(ref_loss), rtol=2e-5, atol=2e-5
        )


def test_moe_serving_engine_paged_decode():
    """The serving engine runs MoE end-to-end: paged decode must reproduce
    the dense forward's greedy tokens, and PD-disagg prefix reuse works."""
    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models.moe import moe_decode_forward

    params = init_moe_params(CFG, jax.random.PRNGKey(0))
    pc = PagedCacheConfig(
        n_layers=CFG.n_layers, n_kv_heads=CFG.n_kv_heads,
        head_dim=CFG.head_dim, n_blocks=16, block_tokens=4, dtype=CFG.dtype,
    )
    eng = InferenceEngine(
        params, CFG, pc, conn=None, model_id="moe",
        prefill_fn=moe_prefill_forward, decode_fn=moe_decode_forward,
    )
    prompt = list(np.random.default_rng(5).integers(0, CFG.vocab_size, 10))
    out = eng.generate(prompt, 4)

    from conftest import make_dense_greedy

    dense = make_dense_greedy(params, CFG, forward=moe_prefill_forward)
    assert out == dense(prompt, 4)


def test_moe_windowed_paged_decode_matches_dense():
    """sliding_window on MoEConfig must behave like the dense family: the
    paged decode mask agrees with the prefill mask (Mixtral v0.1 ships
    sliding_window=4096)."""
    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models.moe import moe_decode_forward

    wcfg = scaled_moe(TINY_MOE, dtype=jnp.float32, sliding_window=6)
    params = init_moe_params(wcfg, jax.random.PRNGKey(3))
    pc = PagedCacheConfig(
        n_layers=wcfg.n_layers, n_kv_heads=wcfg.n_kv_heads,
        head_dim=wcfg.head_dim, n_blocks=16, block_tokens=4, dtype=wcfg.dtype,
    )
    eng = InferenceEngine(
        params, wcfg, pc, conn=None, model_id="moe-w",
        prefill_fn=moe_prefill_forward, decode_fn=moe_decode_forward,
    )
    prompt = list(np.random.default_rng(7).integers(0, wcfg.vocab_size, 10))
    out = eng.generate(prompt, 5)

    from conftest import make_dense_greedy

    dense = make_dense_greedy(params, wcfg, forward=moe_prefill_forward)
    assert out == dense(prompt, 5)

    # and the window must actually change the model vs full causal
    fl, _ = moe_prefill_forward(
        params, scaled_moe(wcfg, sliding_window=None),
        jnp.asarray(prompt, jnp.int32)[None],
    )
    wl, _ = moe_prefill_forward(params, wcfg, jnp.asarray(prompt, jnp.int32)[None])
    assert not np.allclose(np.asarray(fl[0, -1]), np.asarray(wl[0, -1]),
                           rtol=1e-4, atol=1e-4)


def test_shared_experts_forward_and_serving():
    """DeepSeek-MoE-style shared experts (n_shared_experts > 0): the
    always-on FFN adds ungated capacity — the output must differ from
    the pure-routed model with identical routed weights, the paged
    serving engine must decode it consistently with the dense forward,
    and n_shared_experts=0 keeps the param pytree unchanged."""
    from conftest import make_dense_greedy
    from infinistore_tpu.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models.moe import (
        moe_decode_forward,
        moe_verify_forward,
    )

    scfg = scaled_moe(CFG, n_shared_experts=2)
    sparams = init_moe_params(scfg, jax.random.PRNGKey(0))
    assert "ws_gate" in sparams["layers"]
    # same seed, no shared experts: routed weights identical, output not
    params0 = init_moe_params(CFG, jax.random.PRNGKey(0))
    assert "ws_gate" not in params0["layers"]
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (2, 12), 0, scfg.vocab_size)
    lg_s, _ = moe_prefill_forward(sparams, scfg, tokens)
    lg_0, _ = moe_prefill_forward(params0, CFG, tokens)
    assert not np.allclose(np.asarray(lg_s), np.asarray(lg_0))

    # serving: paged decode must follow the dense greedy trajectory
    pc = PagedCacheConfig(
        n_layers=scfg.n_layers, n_kv_heads=scfg.n_kv_heads,
        head_dim=scfg.head_dim, n_blocks=32, block_tokens=4,
        dtype=scfg.dtype,
    )
    eng = InferenceEngine(
        sparams, scfg, pc,
        prefill_fn=moe_prefill_forward, decode_fn=moe_decode_forward,
        verify_fn=moe_verify_forward,
    )
    dense = make_dense_greedy(sparams, scfg, forward=moe_prefill_forward)
    prompt = [int(t) for t in tokens[0][:8]]
    assert eng.generate(prompt, 10) == dense(prompt, 10)


def test_shared_experts_expert_parallel_matches_dense():
    """ep sharding with shared experts: routed experts shard over ep,
    shared weights replicate and must be added OUTSIDE the psum —
    logits must equal the single-device dense forward exactly."""
    scfg = scaled_moe(CFG, n_shared_experts=1)
    mesh = make_moe_mesh(dp=2, ep=4)
    params = init_moe_params(scfg, jax.random.PRNGKey(1))
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (4, 16), 0, scfg.vocab_size)

    ref_logits, _ = moe_prefill_forward(params, scfg, tokens)
    sharded = jax.device_put(
        params, shardings_for(mesh, moe_param_specs(scfg)))
    tok_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P("dp", None)))
    got = make_moe_forward(scfg, mesh)(sharded, tok_sharded)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref_logits), rtol=2e-5, atol=2e-5)
