"""Unit tests for the store core (semantics per reference src/infinistore.cpp)."""

import pytest

from infinistore_tpu import protocol as P
from infinistore_tpu.config import ServerConfig
from infinistore_tpu.store import Store


def make_store(prealloc_mb=1, block_kb=16, **kw):
    cfg = ServerConfig(
        service_port=1, manage_port=1, prealloc_size=1, minimal_allocate_size=block_kb, **kw
    )
    # shrink the pool for tests: bypass the GB unit
    cfg.prealloc_size = 0
    store = Store.__new__(Store)
    import time as _time

    from infinistore_tpu.mempool import MM
    from infinistore_tpu.store import CacheAnalytics, Stats
    from collections import OrderedDict

    store.config = cfg
    store.mm = MM(pool_size=prealloc_mb << 20, block_size=block_kb << 10)
    store.kv = OrderedDict()
    store.pending = {}
    store._deferred = []
    store.stats = Stats()
    store.disk = None
    store._clock = _time.monotonic
    store.analytics = CacheAnalytics()
    store._init_integrity(cfg)  # integrity plane state (epoch, backlog)
    return store


def make_tiered_store(tmp_path, prealloc_mb=1, block_kb=16, disk_slots=64):
    """A store with the disk spill tier attached (tiny capacities)."""
    from infinistore_tpu.store import DiskTier

    s = make_store(prealloc_mb=prealloc_mb, block_kb=block_kb)
    s.disk = DiskTier(str(tmp_path), disk_slots * (block_kb << 10),
                      block_kb << 10)
    return s


@pytest.fixture
def store():
    s = make_store()
    yield s
    s.close()


def test_put_get_inline(store):
    assert store.put_inline(b"k", b"hello world") == P.FINISH
    assert bytes(store.get_inline(b"k")) == b"hello world"
    assert store.get_inline(b"missing") is None


def test_overwrite_inline(store):
    store.put_inline(b"k", b"aaaa")
    store.put_inline(b"k", b"bb")
    assert bytes(store.get_inline(b"k")) == b"bb"
    assert store.kvmap_len() == 1


def test_alloc_commit_visibility(store):
    status, descs = store.alloc_put([b"k1", b"k2"], 1024)
    assert status == P.FINISH and len(descs) == 2
    # uncommitted entries are invisible (reference: kv_map insert at commit)
    assert not store.exist(b"k1")
    st, _ = store.get_desc([b"k1"])
    assert st == P.KEY_NOT_FOUND
    status, count = store.commit_put([b"k1", b"k2"])
    assert status == P.FINISH and count == 2
    assert store.exist(b"k1") and store.exist(b"k2")


def test_get_desc_any_missing_404(store):
    store.put_inline(b"a", b"1234")
    st, descs = store.get_desc([b"a", b"nope"])
    assert st == P.KEY_NOT_FOUND and descs == []


def test_get_desc_size_check(store):
    # stored entry bigger than reader's block size -> INVALID_REQ
    # (reference: src/infinistore.cpp:620-624)
    store.put_inline(b"big", b"x" * 4096)
    st, _ = store.get_desc([b"big"], block_size=1024)
    assert st == P.INVALID_REQ
    st, descs = store.get_desc([b"big"], block_size=4096)
    assert st == P.FINISH and descs[0][2] == 4096


def test_match_last_index(store):
    for k in (b"k0", b"k1", b"k2"):
        store.put_inline(k, b"v")
    assert store.match_last_index([b"k0", b"k1", b"k2", b"x", b"y"]) == 2
    assert store.match_last_index([b"x", b"y"]) == -1
    # reference test shape (test_infinistore.py:291-311)
    assert store.match_last_index([b"A", b"B", b"C", b"k1", b"D", b"E"]) == 3


def test_delete_keys(store):
    for k in (b"a", b"b", b"c"):
        store.put_inline(k, b"v")
    assert store.delete_keys([b"a", b"c", b"zz"]) == 2
    assert not store.exist(b"a")
    assert store.exist(b"b")


def test_purge_and_reuse(store):
    for i in range(5):
        store.put_inline(f"k{i}".encode(), b"v" * 100)
    assert store.purge() == 5
    assert store.kvmap_len() == 0
    assert store.usage() == 0.0
    assert store.put_inline(b"new", b"v") == P.FINISH


def test_lru_eviction_order(store):
    # fill half the 1 MB pool (stay under the on-demand evict threshold)
    for i in range(32):
        assert store.put_inline(f"k{i}".encode(), b"x" * (16 << 10)) == P.FINISH
    # touch k0 so it becomes MRU
    assert store.get_inline(b"k0") is not None
    store.kv[b"k0"].lease = 0  # drop the read lease for this test
    evicted = store.evict(0.25, 0.4)
    assert evicted > 0
    # k0 was MRU: survives; k1 (LRU head) evicted
    assert store.exist(b"k0")
    assert not store.exist(b"k1")


def test_on_demand_evict_on_pressure(store):
    # pool = 64 blocks; fill it, then keep writing: old entries are evicted
    for i in range(64):
        assert store.put_inline(f"k{i}".encode(), b"x" * (16 << 10)) == P.FINISH
    assert store.put_inline(b"overflow", b"y" * (16 << 10)) == P.FINISH
    assert store.exist(b"overflow")


def test_oom_without_auto_increase(store):
    # allocation larger than the whole pool
    st, _ = store.alloc_put([b"huge"], 2 << 20)
    assert st == P.OUT_OF_MEMORY


def test_auto_extend():
    s = make_store(auto_increase=True)
    s.config.auto_increase = True
    # patch extend size down for the test
    import infinistore_tpu.mempool as mp

    orig = mp.EXTEND_POOL_SIZE
    mp.EXTEND_POOL_SIZE = 1 << 20
    try:
        # leases on freshly-read entries block eviction; just fill the pool
        for i in range(64):
            assert s.put_inline(f"k{i}".encode(), b"x" * (16 << 10)) == P.FINISH
        # evicting is possible, but extension path triggers when alloc fails
        s.mm.need_extend = True
        assert s.maybe_extend()
        assert len(s.mm.pools) == 2
    finally:
        mp.EXTEND_POOL_SIZE = orig
        s.close()


def test_stats(store):
    store.put_inline(b"k", b"hello")
    store.get_inline(b"k")
    store.get_inline(b"nope")
    d = store.stats_dict()
    assert d["puts"] == 1 and d["hits"] == 1 and d["misses"] == 1
    assert d["kvmap_len"] == 1


def test_delete_leased_key_defers_free(store):
    """Deleting a key mid shm-read (active lease) must hide the key at once
    but keep the blocks until the lease lapses (a client may be memcpying)."""
    assert store.put_inline(b"k", b"x" * (16 << 10)) == P.FINISH
    st, _ = store.get_desc([b"k"])  # grants the 5 s read lease
    assert st == P.FINISH
    used_before = store.mm.usage()
    assert store.delete_keys([b"k"]) == 1
    assert not store.exist(b"k")  # key gone immediately
    assert store.mm.usage() == used_before  # blocks still held
    assert len(store._deferred) == 1
    # force the lease to lapse, then any reaping op frees the region
    store._deferred[0] = (0.0, store._deferred[0][1])
    store.evict(0.0, 2.0)  # below max threshold: only reaps
    assert store.mm.usage() < used_before
    assert not store._deferred


def test_purge_leased_key_defers_free(store):
    assert store.put_inline(b"k", b"x" * (16 << 10)) == P.FINISH
    st, _ = store.get_desc([b"k"])
    assert st == P.FINISH
    assert store.purge() == 1
    assert store.kvmap_len() == 0
    assert len(store._deferred) == 1


def test_alloc_put_batch_is_contiguous(store):
    """Batch ALLOC_PUT on an unfragmented pool returns descs that form one
    ascending contiguous run in one pool (what the client's run merge and
    the pyserver's streaming merge rely on for bulk copies), and the
    contig_batches stat counts it."""
    keys = [f"cg{i}".encode() for i in range(16)]
    status, descs = store.alloc_put(keys, 16 << 10)
    assert status == P.FINISH
    assert len({p for p, _, _ in descs}) == 1
    base = descs[0][1]
    assert [off for _, off, _ in descs] == [
        base + i * (16 << 10) for i in range(16)
    ]
    assert store.stats_dict()["contig_batches"] == 1
    store.commit_put(keys)
    # fragmented pool (64 blocks total): no contiguous run of 50 exists
    # (largest is the 48-block tail), so the batch falls back to the
    # per-region allocator and still succeeds
    for k in keys[::2]:
        store.delete_keys([k])
    status, descs2 = store.alloc_put(
        [f"fr{i}".encode() for i in range(50)], 16 << 10
    )
    assert status == P.FINISH and len(descs2) == 50
    assert store.stats_dict()["contig_batches"] == 1  # unchanged


# ---- disk spill tier ("Historical KVCache in DRAM and SSD") ----


def test_disk_tier_spill_and_promote(tmp_path):
    s = make_tiered_store(tmp_path)
    payloads = {f"k{i}".encode(): bytes([i]) * (16 << 10) for i in range(32)}
    for k, data in payloads.items():
        assert s.put_inline(k, data) == P.FINISH
    for k in payloads:  # read leases would block eviction
        s.kv[k].lease = 0
    evicted = s.evict(0.25, 0.4)
    assert evicted > 0
    assert s.stats.spilled == evicted  # every evicted entry spilled
    assert len(s.disk) == evicted
    # a spilled entry is still present and reads back byte-identical
    # (promotion pulls it into DRAM and takes it off the disk index)
    victim = next(k for k in payloads if k not in s.kv)
    assert s.exist(victim)
    assert bytes(s.get_inline(victim)) == payloads[victim]
    assert victim in s.kv and victim not in s.disk
    assert s.stats.promoted == 1
    d = s.stats_dict()
    assert d["disk_spilled"] == evicted and d["disk_promoted"] == 1
    s.close()
    import os

    # spill files + manifest PERSIST across close — the warm-restart
    # contract (a restarted node boots with its spilled index intact)
    assert os.path.exists(s.disk.manifest_path)


def test_disk_tier_serves_get_desc_and_prefix_match(tmp_path):
    s = make_tiered_store(tmp_path)
    keys = [f"c{i}".encode() for i in range(24)]
    for k in keys:
        assert s.put_inline(k, b"z" * (16 << 10)) == P.FINISH
    for k in keys:
        s.kv[k].lease = 0
    assert s.evict(0.1, 0.2) > 0
    # the prefix match sees BOTH tiers: reuse survives memory pressure
    assert s.match_last_index(keys + [b"absent"]) == len(keys) - 1
    # zero-copy descriptors promote on demand
    cold = next(k for k in keys if k not in s.kv)
    st, descs = s.get_desc([cold])
    assert st == P.FINISH and len(descs) == 1
    pool_idx, offset, size = descs[0]
    assert bytes(s.mm.view(pool_idx, offset, size)) == b"z" * (16 << 10)
    s.close()


def test_disk_tier_delete_purge_and_overwrite(tmp_path):
    s = make_tiered_store(tmp_path)
    for i in range(24):
        s.put_inline(f"k{i}".encode(), b"a" * (16 << 10))
    for k in list(s.kv):
        s.kv[k].lease = 0
    s.evict(0.1, 0.2)
    cold = next(iter(s.disk.index))
    # delete reaches the disk tier too
    assert s.delete_keys([cold]) == 1
    assert not s.exist(cold)
    # a fresh commit supersedes a stale spilled copy
    cold2 = next(iter(s.disk.index))
    assert s.put_inline(cold2, b"NEW" * 16) == P.FINISH
    assert cold2 not in s.disk
    assert bytes(s.get_inline(cold2)) == b"NEW" * 16
    # purge clears both tiers
    assert len(s.disk) > 0
    s.purge()
    assert len(s.disk) == 0 and s.kvmap_len() == 0
    s.close()


def test_disk_tier_capacity_drops_oldest(tmp_path):
    from infinistore_tpu.store import DiskTier

    tier = DiskTier(str(tmp_path), 4 * 1024, 1024)  # 4 slots
    for i in range(6):
        assert tier.put(f"k{i}".encode(), bytes([i]) * 100)
    assert len(tier) == 4 and tier.dropped == 2
    assert tier.get(b"k0") is None and tier.get(b"k1") is None  # oldest out
    assert tier.get(b"k5") == bytes([5]) * 100
    tier.close()


def test_disk_tier_multiblock_entries_spill(tmp_path):
    """Entries spanning several pool blocks (contiguous multi-block DRAM
    regions) must spill and promote too — the slab allocates consecutive
    slot runs, not single slots (regression: they used to vanish)."""
    s = make_tiered_store(tmp_path)
    big = bytes(range(256)) * 192  # 48 KB = 3 x 16 KB blocks
    for i in range(8):
        assert s.put_inline(f"big{i}".encode(), big) == P.FINISH
    for k in list(s.kv):
        s.kv[k].lease = 0
    evicted = s.evict(0.1, 0.2)
    assert evicted > 0
    assert s.stats.spilled == evicted  # nothing vanished
    cold = next(k for k in (f"big{i}".encode() for i in range(8))
                if k not in s.kv)
    assert s.exist(cold)
    assert bytes(s.get_inline(cold)) == big  # byte-identical round trip
    assert s.stats_dict()["disk_bytes"] == (evicted - 1) * len(big)
    s.close()


def test_disk_tier_mixed_batch_get_desc_promotes_safely(tmp_path):
    """get_desc over a batch mixing resident and spilled keys under memory
    pressure: promotion-triggered eviction must never free a batchmate's
    region mid-request (regression: KeyError / stale descriptor)."""
    s = make_tiered_store(tmp_path, disk_slots=128)
    data = {}
    for i in range(60):  # fill most of the 64-block pool
        k = f"m{i}".encode()
        data[k] = bytes([i]) * (16 << 10)
        assert s.put_inline(k, data[k]) == P.FINISH
    for k in list(s.kv):
        s.kv[k].lease = 0
    s.evict(0.3, 0.4)  # spill a cold prefix
    cold = [k for k in data if k not in s.kv][:4]
    hot = [k for k in data if k in s.kv][:4]
    for k in hot:
        s.kv[k].lease = 0  # expired leases: evictable unless re-leased
    batch = hot + cold  # promotions happen AFTER hot keys joined the batch
    st, descs = s.get_desc(batch)
    assert st == P.FINISH and len(descs) == len(batch)
    for k, (pool_idx, offset, size) in zip(batch, descs):
        assert bytes(s.mm.view(pool_idx, offset, size)) == data[k]
    s.close()


def test_sizeclass_pressure_evict_frees_full_class():
    """sizeclass mode: one class's pools can be FULL while global usage
    is low, so the usage-gated evict never fires — allocation failure
    must pop LRU entries (reaching the full class's own) instead of
    answering OUT_OF_MEMORY while evictable data sits in the way."""
    store = make_store(prealloc_mb=1, block_kb=16)
    store.mm.close()
    from infinistore_tpu.mempool import MM

    store.mm = MM(pool_size=1 << 20, block_size=16 << 10,
                  allocator="sizeclass")
    try:
        # fill the 16 KB class: 1 MB budget / 16 KB = 64 entries max;
        # carve chunks mean the class saturates well before the budget
        # is globally "full"
        i = 0
        while store.put_inline(f"k{i}".encode(), b"x" * (16 << 10)) == P.FINISH:
            i += 1
            if i > 80:
                break
        assert i >= 16  # several carves landed
        # keep putting: pressure eviction must keep these succeeding
        # (old entries of the same class evict, LRU first)
        for j in range(10):
            assert store.put_inline(
                f"n{j}".encode(), b"y" * (16 << 10)) == P.FINISH
        assert store.get_inline(b"n9") is not None
        assert store.get_inline(b"k0") is None  # LRU victim
    finally:
        store.mm.close()
