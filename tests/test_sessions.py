"""Session-grain observability (`sessions.py` + the conversation-mode
loadgen + session affinity at the front door).

Chaos half FIRST (house rule — the FaultInjector action is armed before
any mitigation): a mid-conversation decode-worker drain breaks session
affinity — the router counts the `miss`, re-pins the session to the
survivor, the survivor serves turn N+1 FROM THE STORE (adoption
provenance, not recompute), and the fleet-wide re-prefill waste delta
stays 0: the KV-persistence contract survives the worker death.

Pure half: the `SessionLedger` waste math (warm ~0, cold linear), the
LRU bound with exact lifetime totals, the derived metric families, the
conversation-mode loadgen (deterministic populations, strict
prefix-growth, the TTFT-vs-turn slope), the `reprefill_waste` watchdog
rule, the istpu-top session view, and the doctor's sessions summary.

Live half: `/debug/sessions` + validation on a monolith server, THE
tier-1 persistence-contract walk (store holding turns 1..N-1 makes
turn-N prefill adopt instead of recompute — near-flat vs a cold
control's linear growth), and the slow ROADMAP-5 sweep (500 sessions x
8 turns through a disaggregated fleet).
"""

import json
import http.client
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from infinistore_tpu.utils.metrics import MetricsRegistry, \
    parse_prometheus_text


# ---------------------------------------------------------------------------
# synthetic requests for the pure ledger tests
# ---------------------------------------------------------------------------


class _St:
    def __init__(self, local_chunks=0, store_chunks=0):
        self.local_chunks = local_chunks
        self.store_chunks = store_chunks


class _Req:
    """The slice of scheduler.Request the ledger reads."""

    def __init__(self, session, tokens, local=0, store=0, tenant=None,
                 priority=0, req_id=1, ttft=0.01):
        self.session = session
        self.tokens = list(tokens)
        self.tenant = tenant
        self.priority = priority
        self.req_id = req_id
        self.trace_id = f"tr-{req_id}"
        self.t_submit = 100.0
        self.t_first = 100.0 + ttft if ttft is not None else None
        self.state = _St(local, store)


def test_session_ledger_waste_math_warm_vs_cold():
    """The headline derivation: a warm session (every turn's prior
    context reused from local/store pages) pays zero waste while context
    accumulates; a cold session re-pays the whole overlap each turn."""
    from infinistore_tpu.sessions import SessionLedger

    led = SessionLedger(capacity=8, block_tokens=16)
    # warm: turn 1 computes 64 fresh (no prior turn -> overlap 0);
    # turn 2 extends to 128 with the first 64 reused (4 store chunks)
    row1 = led.record_turn(_Req("warm", range(64)), "completed")
    assert row1["turn"] == 1 and row1["overlap_tokens"] == 0
    assert row1["waste_tokens"] == 0 and row1["computed_tokens"] == 64
    row2 = led.record_turn(_Req("warm", range(128), store=4), "completed")
    assert row2["turn"] == 2
    assert row2["overlap_tokens"] == 64 and row2["store_tokens"] == 64
    assert row2["computed_tokens"] == 64 and row2["waste_tokens"] == 0
    # cold: same shape, zero reuse -> the 64-token overlap was recomputed
    led.record_turn(_Req("cold", range(64)), "completed")
    rowc = led.record_turn(_Req("cold", range(128)), "completed")
    assert rowc["computed_tokens"] == 128
    assert rowc["waste_tokens"] == 64  # exactly the re-paid context
    assert led.waste_tokens == 64 and led.computed_tokens == 320
    snap = led.snapshot()
    assert snap["totals"]["waste_tokens"] == 64
    assert snap["totals"]["reprefill_waste_frac"] == round(64 / 320, 4)
    # waste never exceeds what was computed (over-reported reuse clamps)
    led.record_turn(_Req("warm", range(144), local=8, store=0),
                    "completed")
    ent = [e for e in led.snapshot()["sessions"]
           if e["session"] == "warm"][0]
    assert ent["rows"][-1]["waste_tokens"] == 0  # reused covers overlap


def test_session_ledger_sessionless_requests_are_ignored():
    from infinistore_tpu.sessions import SessionLedger

    led = SessionLedger(capacity=4, block_tokens=4)
    req = _Req(None, range(8))
    assert led.record_turn(req, "completed") is None
    req.session = ""
    assert led.record_turn(req, "completed") is None
    assert led.recorded_turns == 0 and led.snapshot()["sessions"] == []


def test_session_ledger_lru_bound_and_exact_totals():
    """Capacity evicts least-recently-ACTIVE sessions; the lifetime
    tallies stay exact after entries scroll away (same discipline as the
    request ledger's ring)."""
    from infinistore_tpu.sessions import SessionLedger

    led = SessionLedger(capacity=3, block_tokens=4, max_turns=2)
    for i in range(7):
        led.record_turn(_Req(f"s{i}", range(8), req_id=i), "completed")
    # a touch makes s4 most-recent (survives while s5 is evicted later)
    led.record_turn(_Req("s4", range(16), req_id=99), "completed")
    led.record_turn(_Req("s7", range(8), req_id=7), "completed")
    snap = led.snapshot()
    names = [e["session"] for e in snap["sessions"]]
    assert len(names) == 3 and names[-1] == "s7" and "s4" in names
    assert snap["recorded_sessions"] == 8
    assert snap["totals"]["turns"] == 9  # exact despite 5 evictions
    # the per-session turn ring is bounded but the turn COUNTER is not
    for t in range(5):
        led.record_turn(_Req("s7", range(8 * (t + 2))), "completed")
    ent = [e for e in led.snapshot()["sessions"]
           if e["session"] == "s7"][0]
    assert ent["turns"] == 6 and len(ent["rows"]) == 2  # max_turns=2
    assert ent["rows"][-1]["turn"] == 6


def test_session_ledger_snapshot_shape_limit_and_active_window():
    from infinistore_tpu.sessions import ACTIVE_WINDOW_S, SessionLedger

    led = SessionLedger(capacity=8, block_tokens=4)
    led.record_turn(_Req("old", range(8)), "completed", wall=1800.0)
    led.record_turn(_Req("new", range(8)), "completed", wall=2000.0)
    snap = led.snapshot(limit=1)
    assert snap["returned"] == 1
    assert snap["sessions"][0]["session"] == "new"  # newest-last slice
    assert set(snap) >= {"enabled", "capacity", "block_tokens",
                         "recorded_sessions", "active_sessions",
                         "totals", "sessions"}
    row = snap["sessions"][0]["rows"][0]
    assert set(row) >= {"turn", "req_id", "trace_id", "outcome",
                        "prompt_tokens", "new_tokens", "ttft_s",
                        "local_tokens", "store_tokens",
                        "computed_tokens", "overlap_tokens",
                        "waste_tokens"}
    # the active gauge is a WINDOW over last_seen, not an LRU property
    assert led.active_count(now=2000.0) == 2
    assert led.active_count(now=1800.0 + ACTIVE_WINDOW_S + 1) == 1
    assert led.active_count(now=2000.0 + ACTIVE_WINDOW_S + 1) == 0


def test_session_ledger_metric_families():
    """The derived families: per-tenant turn/waste counters (the waste
    series pre-created at turn 1 so watchdog deltas never read an absent
    family), the active-sessions gauge, and the banded TTFT histogram."""
    from infinistore_tpu.sessions import SessionLedger, ttft_band

    assert [ttft_band(t) for t in (1, 2, 3, 4, 7, 8, 100)] == \
        ["1", "2-3", "2-3", "4-7", "4-7", "8+", "8+"]
    reg = MetricsRegistry()
    led = SessionLedger(capacity=8, block_tokens=16, metrics=reg)
    led.record_turn(_Req("s", range(64), tenant="acme", ttft=0.05),
                    "completed")
    led.record_turn(_Req("s", range(128), tenant="acme", ttft=0.06),
                    "completed")  # cold turn 2: waste 64
    text = reg.to_prometheus_text()
    parsed = parse_prometheus_text(text)

    def fam(name, **labels):
        return parsed.get(
            (name, tuple(sorted((k, str(v)) for k, v in labels.items()))))

    assert fam("istpu_serve_session_turns_total", tenant="acme") == 2.0
    assert fam("istpu_serve_reprefill_waste_tokens_total",
               tenant="acme") == 64.0
    assert fam("istpu_serve_active_sessions") == 1.0
    assert fam("istpu_serve_session_turn_ttft_seconds_count",
               band="1") == 1.0
    assert fam("istpu_serve_session_turn_ttft_seconds_count",
               band="2-3") == 1.0
    # every band series exists before deep turns land (pre-created)
    assert fam("istpu_serve_session_turn_ttft_seconds_count",
               band="8+") == 0.0


def test_reprefill_waste_watchdog_rule():
    """The persistence contract as an alert: fires on a sustained waste
    fraction over budget, stays silent below the volume guard (single
    tiny turns must not page) and on warm traffic."""
    from infinistore_tpu.health import TimeSeriesRing, burn_windows, \
        reprefill_waste_rule

    slow = burn_windows()[1]
    rule = reprefill_waste_rule(budget_frac=0.25, min_tokens=1000.0)
    assert rule.name == "reprefill_waste" and rule.severity == "warn"
    r = TimeSeriesRing(step_s=1.0, clock=lambda: 0.0)
    # below the volume guard: 500 computed, all waste -> silent
    r.observe("serve.session_computed", 0.0, t=0.0)
    r.observe("serve.reprefill_waste", 0.0, t=0.0)
    r.observe("serve.session_computed", 500.0, t=10.0)
    r.observe("serve.reprefill_waste", 500.0, t=10.0)
    assert rule.check(r, 10.0) is None
    # warm at volume: 4000 computed, 2% waste -> silent
    r.observe("serve.session_computed", 4500.0, t=20.0)
    r.observe("serve.reprefill_waste", 580.0, t=20.0)
    assert rule.check(r, 20.0) is None
    # cold at volume: 40% of the window's computed tokens were re-paid
    r.observe("serve.session_computed", 14500.0, t=min(30.0, slow - 1))
    r.observe("serve.reprefill_waste", 4580.0, t=min(30.0, slow - 1))
    res = rule.check(r, min(30.0, slow - 1))
    assert res is not None and res["value"] >= 0.25
    assert "re-prefill waste" in res["reason"]
    # and it ships in the default serve set
    from infinistore_tpu.health import default_serve_rules
    assert "reprefill_waste" in [x.name for x in default_serve_rules()]


# ---------------------------------------------------------------------------
# conversation-mode loadgen (pure: injected post, no server)
# ---------------------------------------------------------------------------


def test_make_sessions_deterministic_with_shared_system_prompt():
    from infinistore_tpu.loadgen import SessionConfig, make_sessions

    cfg = SessionConfig(n_sessions=8, seed=3, turns=((1.0, 2), (1.0, 5)),
                        turn_tokens=((1.0, 4), (1.0, 12)),
                        system_prompt_len=16,
                        lanes=((0, 0.8), (3, 0.2)))
    a, b = make_sessions(cfg), make_sessions(cfg)
    assert a == b  # deterministic in the seed
    assert make_sessions(SessionConfig(n_sessions=8, seed=4)) != a
    systems = {tuple(s["system"]) for s in a}
    assert len(systems) == 1  # the population-wide shared prefix
    assert len(next(iter(systems))) == 16
    assert {s["session"] for s in a} == {f"s3-{i:04d}" for i in range(8)}
    assert {len(s["turns"]) for s in a} <= {2, 5}
    assert {s["lane"] for s in a} <= {0, 3}
    for s in a:
        for t in s["turns"]:
            assert len(t["user_tokens"]) in (4, 12)
            assert t["think_s"] == 0.0  # think range (0, 0)


def test_run_sessions_prefix_growth_and_summary():
    """Each turn's prompt is the accumulated context plus this turn's
    tokens (the strict-prefix property store reuse depends on), every
    body carries the session id, and the summary's per-turn table and
    TTFT slope reduce the rows."""
    from infinistore_tpu.loadgen import SessionConfig, run_sessions, \
        session_summary

    cfg = SessionConfig(rate=1000.0, n_sessions=3, seed=5,
                        turns=((1.0, 3),), turn_tokens=((1.0, 4),),
                        system_prompt_len=8, max_tokens=2,
                        extra_body={"tenant": "acme"})
    bodies, lock = [], threading.Lock()

    def post(body):
        with lock:
            bodies.append(body)
        turn = (len(body["prompt"]) - 8) // 4  # ttft grows with depth
        return {"ok": True, "status": 200, "tokens": 2,
                "lane": body["priority"], "rejected": False,
                "retry_after_s": None, "ttft_s": 0.010 * turn,
                "tpot_s": 0.001, "e2e_s": 0.02, "error": None}

    results, makespan = run_sessions("http://ignored", cfg, post=post)
    assert len(results) == 9 and makespan > 0
    by_sid = {}
    for b in bodies:
        assert b["temperature"] == 0 and b["tenant"] == "acme"
        by_sid.setdefault(b["session"], []).append(b["prompt"])
    assert len(by_sid) == 3
    for prompts in by_sid.values():
        prompts.sort(key=len)
        assert [len(p) for p in prompts] == [12, 16, 20]
        for a, b in zip(prompts, prompts[1:]):
            assert b[:len(a)] == a  # strict prefix growth
    # rows are tagged for the summary join
    assert sorted(r["turn"] for r in results) == [1, 1, 1, 2, 2, 2, 3, 3, 3]
    assert all(r["prompt_tokens"] == 8 + 4 * r["turn"] for r in results)
    s = session_summary(results)
    assert s["sessions"] == 3 and s["completed"] == 9
    assert s["per_turn"]["1"] == {"n": 3, "completed": 3,
                                  "ttft_mean_ms": 10.0}
    # ttft = 10ms * turn -> the least-squares slope is exactly 10
    assert s["ttft_slope_ms_per_turn"] == pytest.approx(10.0)


def test_session_summary_flat_vs_growing_and_tombstones():
    from infinistore_tpu.loadgen import session_summary

    flat = [{"ok": True, "turn": t, "ttft_s": 0.02}
            for t in (1, 2, 3, 4) for _ in range(3)]
    assert session_summary(flat)["ttft_slope_ms_per_turn"] == \
        pytest.approx(0.0)
    # failed turns count in n but not in the TTFT means
    rows = [{"ok": True, "turn": 1, "ttft_s": 0.01},
            {"ok": False, "turn": 2, "ttft_s": None, "error": "timeout"},
            {"ok": True, "turn": 2, "ttft_s": 0.03}]
    s = session_summary(rows)
    assert s["per_turn"]["2"] == {"n": 2, "completed": 1,
                                  "ttft_mean_ms": 30.0}


# ---------------------------------------------------------------------------
# operator surfaces: the istpu-top session view + the doctor summary
# ---------------------------------------------------------------------------


def _sessions_payload(turns=10, waste=0, frac=0.0):
    return {
        "enabled": True, "capacity": 256, "block_tokens": 4,
        "recorded_sessions": 3, "active_sessions": 2, "returned": 2,
        "totals": {"turns": turns, "waste_tokens": waste,
                   "overlap_tokens": 400, "reused_tokens": 400 - waste,
                   "computed_tokens": 500,
                   "reprefill_waste_frac": frac},
        "sessions": [
            {"session": "conv-a", "tenant": "acme", "turns": 6,
             "max_prompt_tokens": 288, "waste_tokens": waste,
             "rows": []},
            {"session": "conv-b", "tenant": "bob", "turns": 4,
             "max_prompt_tokens": 160, "waste_tokens": 0, "rows": []},
        ],
    }


def test_console_renders_session_view():
    """The session section of istpu-top: active/turn/waste headline with
    per-frame deltas, the affinity hit share among re-visits (fallback
    is every session's FIRST placement — excluded from the
    denominator), and the newest session rows."""
    from infinistore_tpu.top import Console, Snapshot

    reg = MetricsRegistry()
    c = reg.counter("istpu_serve_session_affinity_total", "",
                    labelnames=("result",))
    c.labels("hit").inc(8)
    c.labels("miss").inc(2)
    c.labels("fallback").inc(90)  # must NOT dilute the hit share
    serve = parse_prometheus_text(reg.to_prometheus_text())

    console = Console()
    first = console.frame(Snapshot(serve_metrics=serve,
                                   sessions=_sessions_payload(10, 0)))
    assert "sessions  active     2" in first
    out = console.frame(Snapshot(
        serve_metrics=serve,
        sessions=_sessions_payload(turns=16, waste=30, frac=0.06)))
    assert "turns      16 (+6/frame)" in out
    assert "waste-frac   6.0%" in out and "Δwaste-tok +30" in out
    assert "affinity hit 80.0%" in out  # 8/(8+2), fallback excluded
    assert "conv-a" in out and "acme" in out and "conv-b" in out
    # ledger absent (old server) or disabled: section absent, no crash
    assert "sessions  active" not in Console().frame(Snapshot())
    assert "sessions  active" not in Console().frame(
        Snapshot(sessions={"enabled": False}))


def test_doctor_summary_renders_sessions_section():
    from infinistore_tpu.doctor import SERVE_ENDPOINTS, summarize_capture

    assert any(name == "sessions" and path == "/debug/sessions"
               for name, path, _f in SERVE_ENDPOINTS)

    def cap_with(payload):
        cap = {
            "fetched_at": 0, "stores": [],
            "serve": {
                "url": "http://s", **{
                    name: {"path": p, "file": f, "ok": False,
                           "error": "x", "bytes": 0, "data": None}
                    for name, p, f in SERVE_ENDPOINTS
                },
            },
        }
        cap["serve"]["sessions"] = {
            "path": "/debug/sessions", "file": "debug_sessions.json",
            "ok": True, "error": None, "bytes": 1,
            "data": json.dumps(payload).encode()}
        return cap

    text = summarize_capture(cap_with(_sessions_payload(16, 128, 0.256)))
    assert "## Sessions / re-prefill waste" in text
    assert "3 sessions recorded (2 active), 16 turns" in text
    assert "**25.6%** re-prefill waste" in text
    assert "session conv-a (tenant acme)" in text  # worst offender named
    # a warm capture states the contract HELD instead of listing nobody
    warm = summarize_capture(cap_with(_sessions_payload(16, 0, 0.0)))
    assert "no session paid re-prefill waste" in warm


# ---------------------------------------------------------------------------
# live halves: a store subprocess + in-process servers/fleets
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def live_store():
    port, mport = _free_port(), _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", "python"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    deadline = time.time() + 25
    while True:
        if proc.poll() is not None:
            pytest.fail("store server failed to start")
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.5).close()
            break
        except OSError:
            if time.time() >= deadline:
                proc.kill()
                pytest.fail("store server did not come up")
            time.sleep(0.1)
    yield port
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def _post(port, path, body, timeout=120.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _get(port, path, timeout=30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _metric(prom_text, family, **labels):
    parsed = parse_prometheus_text(prom_text)
    key = (family, tuple(sorted((k, str(v)) for k, v in labels.items())))
    return parsed.get(key)


def _sessions_of(port):
    _s, data = _get(port, "/debug/sessions")
    return json.loads(data)


def test_chaos_decode_drain_mid_conversation(live_store):
    """THE chaos walk (FaultInjector action first, house rule): a
    session is mid-conversation when its pinned decode worker drains —
    drop_conn armed on the victim's /v1/completions, breaker pinned
    open, then the real httpd kill.  The next turn fails over
    IN-REQUEST: the router counts the affinity `miss` and re-pins to
    the survivor, the survivor ADOPTS the accumulated context from the
    store (provenance, not recompute), the fleet-wide re-prefill waste
    delta stays 0, and the turn after that is a `hit` on the new pin —
    placement is an optimization, the store tier is the contract."""
    from infinistore_tpu.frontdoor import local_fleet

    saved = {k: os.environ.get(k)
             for k in ("ISTPU_SLO_TTFT_S", "ISTPU_SLO_TPOT_S")}
    os.environ["ISTPU_SLO_TTFT_S"] = "60"
    os.environ["ISTPU_SLO_TPOT_S"] = "10"
    fd, workers, close = local_fleet(live_store, 1, 2, poll_s=0.3)
    try:
        # warm every worker's compile paths outside the walk
        for w in workers["decode"]:
            status, _ = _post(w.port, "/v1/completions",
                              {"prompt": [7, 7, 7, 7, 7], "max_tokens": 2,
                               "temperature": 0})
            assert status == 200
        status, _ = _post(fd.port, "/v1/completions",
                          {"prompt": [9, 9, 9, 9, 9], "max_tokens": 2,
                           "temperature": 0})
        assert status == 200

        sid = "chaos-conv"
        context = list(range(3, 19))  # 4 complete chunks at block_tokens=4

        def turn(n_new):
            context.extend(range(100 + len(context),
                                 100 + len(context) + n_new))
            status, body = _post(fd.port, "/v1/completions",
                                 {"prompt": list(context), "max_tokens": 2,
                                  "temperature": 0, "session": sid})
            return status, body

        status, _b = turn(0)  # turn 1: fallback placement, then pinned
        assert status == 200
        pinned = fd.session_pin(sid)
        assert pinned, "turn 1 must bind the session"
        status, _b = turn(8)  # turn 2: a hit on the pin
        assert status == 200
        assert fd.session_pin(sid) == pinned
        _s, data = _get(fd.port, "/metrics")
        prom = data.decode()
        assert (_metric(prom, "istpu_serve_session_affinity_total",
                        result="fallback") or 0.0) >= 1.0
        hits_before = _metric(prom, "istpu_serve_session_affinity_total",
                              result="hit") or 0.0
        assert hits_before >= 1.0
        miss_before = _metric(prom, "istpu_serve_session_affinity_total",
                              result="miss") or 0.0

        victim = next(s for s in workers["decode"]
                      if f"127.0.0.1:{s.port}" == pinned)
        survivor = next(s for s in workers["decode"] if s is not victim)
        # waste baseline on every worker that will survive the drain
        waste_before = {
            w.port: _sessions_of(w.port)["totals"]["waste_tokens"]
            for w in [survivor] + workers["prefill"]
        }

        # the FaultInjector action FIRST (house rule): every completion
        # on the victim dies at the socket — the in-flight shape of a
        # drain — before any mitigation runs
        status, out = _post(victim.port, "/debug/faults",
                            [{"op": "/v1/completions",
                              "action": "drop_conn", "times": -1}])
        assert status == 200 and out["armed"] == 1
        # keep the opened circuit visible at assert time (no half-open
        # probe mid-walk)
        victim_state = next(w for w in fd.decode if w.port == victim.port)
        victim_state.breaker.cooldown_s = 300.0
        # then the REAL kill: nothing answers at all
        victim.httpd.shutdown()
        victim.httpd.server_close()

        status, _b = turn(8)  # turn 3: mid-conversation failover
        assert status == 200, "the drain must not surface to the client"
        _s, data = _get(fd.port, "/metrics")
        prom = data.decode()
        assert (_metric(prom, "istpu_serve_session_affinity_total",
                        result="miss") or 0.0) >= miss_before + 1.0
        # the session re-pinned to whoever actually served
        new_pin = fd.session_pin(sid)
        assert new_pin == f"127.0.0.1:{survivor.port}"
        # the survivor served turn 3 FROM THE STORE: adoption
        # provenance on its newest ledger record, not a recompute
        _s, data = _get(survivor.port, "/debug/requests")
        rec = json.loads(data)["records"][-1]
        assert ((rec.get("store") or {}).get("store_chunks") or 0) >= 1, rec
        # and its session ledger row agrees: reuse covered the overlap
        snap = _sessions_of(survivor.port)
        ent = [e for e in snap["sessions"] if e["session"] == sid][0]
        assert ent["rows"][-1]["store_tokens"] >= 16  # turns 1-2 context
        # the KV-persistence contract: waste delta 0 across the fleet
        for w in [survivor] + workers["prefill"]:
            assert _sessions_of(w.port)["totals"]["waste_tokens"] == \
                waste_before[w.port], f"re-prefill waste on :{w.port}"

        status, _b = turn(8)  # turn 4: a hit on the NEW pin
        assert status == 200
        _s, data = _get(fd.port, "/metrics")
        assert (_metric(data.decode(), "istpu_serve_session_affinity_total",
                        result="hit") or 0.0) >= hits_before + 1.0
        # the router's fleet report carries the affinity tallies
        _s, data = _get(fd.port, "/debug/fleet")
        sess = json.loads(data).get("sessions") or {}
        assert sess.get("pinned", 0) >= 1
    finally:
        close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_serve_sessions_endpoint_validation_and_families():
    """The monolith contract: a session-tagged conversation lands in
    GET /debug/sessions (rows joined to the request ledger by trace
    id), the derived families ride /metrics, a malformed session id is
    a 400, and session-less traffic records nothing."""
    import jax
    import jax.numpy as jnp

    from infinistore_tpu.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models import TINY, init_params, scaled
    from infinistore_tpu.serve import ServingServer

    cfg = scaled(TINY, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(7))
    eng = InferenceEngine(
        params, cfg,
        PagedCacheConfig(n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
                         head_dim=cfg.head_dim, n_blocks=64,
                         block_tokens=4, dtype=cfg.dtype),
    )
    old = os.environ.get("ISTPU_ADMISSION")
    os.environ["ISTPU_ADMISSION"] = "0"
    srv = ServingServer(eng, port=0, max_batch=4, model_id="tiny-sess",
                        session_ring=8)
    srv.start()
    try:
        ctx = [11, 42, 7, 99, 5, 3, 17, 28]
        status, _ = _post(srv.port, "/v1/completions",
                          {"prompt": ctx, "max_tokens": 2,
                           "temperature": 0, "session": "conv.A-1"})
        assert status == 200
        status, _ = _post(srv.port, "/v1/completions",
                          {"prompt": ctx + [64, 1, 2, 9], "max_tokens": 2,
                           "temperature": 0, "session": "conv.A-1"})
        assert status == 200
        # session-less traffic does not touch the ledger
        status, _ = _post(srv.port, "/v1/completions",
                          {"prompt": ctx, "max_tokens": 1,
                           "temperature": 0})
        assert status == 200
        snap = _sessions_of(srv.port)
        assert snap["enabled"] and snap["capacity"] == 8
        assert snap["totals"]["turns"] == 2
        ent = snap["sessions"][0]
        assert ent["session"] == "conv.A-1" and ent["turns"] == 2
        rows = ent["rows"]
        assert [r["turn"] for r in rows] == [1, 2]
        assert rows[1]["prompt_tokens"] == 12
        # turn 2 reused turn 1's pages (local, monolith) -> zero waste
        assert rows[1]["local_tokens"] >= 4
        assert rows[1]["waste_tokens"] == 0
        # joined to the request ledger by trace id
        _s, data = _get(srv.port, "/debug/requests")
        traces = {r.get("trace_id") for r in json.loads(data)["records"]}
        assert rows[0]["trace_id"] in traces
        # ?limit= caps the session rows, totals stay exact
        snap1 = json.loads(_get(srv.port, "/debug/sessions?limit=0")[1])
        assert snap1["returned"] == 0 and snap1["totals"]["turns"] == 2
        # the families ride the serving registry
        _s, data = _get(srv.port, "/metrics")
        prom = data.decode()
        assert _metric(prom, "istpu_serve_session_turns_total",
                       tenant="0") == 2.0
        assert _metric(prom, "istpu_serve_reprefill_waste_tokens_total",
                       tenant="0") == 0.0
        assert _metric(prom, "istpu_serve_active_sessions") >= 1.0
        # the tenant/session validation contract: same charset, 400 on
        # anything else, nothing recorded for the rejected request
        for bad in ("bad id", "x" * 65, "sp@ce", ""):
            status, body = _post(srv.port, "/v1/completions",
                                 {"prompt": ctx, "max_tokens": 1,
                                  "temperature": 0, "session": bad})
            assert status == 400, bad
            assert "session" in json.dumps(body)
        assert _sessions_of(srv.port)["totals"]["turns"] == 2
    finally:
        srv.close()
        if old is None:
            os.environ.pop("ISTPU_ADMISSION", None)
        else:
            os.environ["ISTPU_ADMISSION"] = old


def test_kv_persistence_contract_warm_store_vs_cold_control(live_store):
    """THE tier-1 acceptance walk (ROADMAP item 5's contract at engine
    grain): with the store holding turns 1..N-1 of an accumulating
    context, turn N's prefill ADOPTS the prior context (store
    provenance, computed stays ~new-tokens — near-flat) while a cold
    control recomputes the whole context every turn (linear).  Each
    turn runs on a FRESH engine so local pages cannot mask the store:
    everything reused had to cross the store tier."""
    import jax
    import numpy as np

    from infinistore_tpu import lib as ist
    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models import TINY, init_params

    cfg = TINY
    params = init_params(cfg, jax.random.PRNGKey(0))

    def make_pc():
        return PagedCacheConfig(
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, block_tokens=16, n_blocks=128,
        )

    rng = np.random.RandomState(11)

    def toks(n):
        return [int(x) for x in rng.randint(1, cfg.vocab_size, size=n)]

    conn = ist.InfinityConnection(ist.ClientConfig(
        host_addr="127.0.0.1", service_port=live_store,
        connection_type=ist.TYPE_SHM, log_level="warning"))
    conn.connect()
    os.environ.setdefault("ISTPU_CLIENT", "python")
    try:
        def conversation():
            """A 4-turn accumulating context: 128-token opener + 64
            new tokens per turn."""
            context, out = toks(128), []
            for _turn in range(4):
                out.append(list(context))
                context = context + toks(64)
            return out

        def run_turns(contexts, attached):
            """One timed prefill per turn on a FRESH engine; returns
            (times, provenance states)."""
            times, states = [], []
            for ctx in contexts:
                e = InferenceEngine(
                    params, cfg, make_pc(),
                    conn=conn if attached else None,
                    model_id="sess-contract", prefill_chunk=64,
                    store_durability="relaxed")
                t0 = time.perf_counter()
                s = e.prefill(list(ctx))
                np.asarray(s.last_logits)
                times.append(time.perf_counter() - t0)
                states.append(s)
                if attached:
                    e.store_flush()  # turns 1..i now held by the store
                e.release(s)
            return times, states

        # warmup: the SAME chain shape on a throwaway context family —
        # compiles (prefill chunks per length AND the adoption scatter,
        # which traces per adopted-page count) are process-wide, so the
        # measured chains below pay transfer + compute only
        _t, wst = run_turns(conversation(), True)
        assert wst[-1].store_chunks >= 1  # the store round-trip works
        run_turns(conversation(), False)

        contexts = conversation()
        lengths = [len(c) for c in contexts]
        assert lengths == [128, 192, 256, 320]
        t_warm, warm_states = run_turns(contexts, True)
        t_cold, cold_states = run_turns(contexts, False)

        # structural (deterministic): every warm turn >= 2 adopted the
        # ENTIRE prior context from the store — fresh engines hold no
        # local pages, so computed stays ~the 64 new tokens (near-flat
        # in token terms) while the cold control recomputed everything
        for i in range(1, len(contexts)):
            st = warm_states[i]
            assert st.local_chunks == 0
            assert st.store_chunks >= lengths[i - 1] // 16, (
                f"turn {i + 1}: adopted {st.store_chunks} chunks, "
                f"expected the {lengths[i - 1] // 16} the store held")
        for st in cold_states:
            assert st.store_chunks == 0 and st.local_chunks == 0
        # timing (aggregate, generous): re-paying the context every
        # turn must cost more wall clock than adopting it — summed over
        # turns 2..N so single-sample host jitter averages out
        assert sum(t_warm[1:]) < sum(t_cold[1:]), (
            f"warm {[f'{t * 1e3:.1f}' for t in t_warm]} ms vs "
            f"cold {[f'{t * 1e3:.1f}' for t in t_cold]} ms "
            f"(loadavg: {os.getloadavg()})"
        )
    finally:
        conn.close()


@pytest.mark.slow
def test_roadmap5_session_sweep_500x8(live_store):
    """ROADMAP item 5's fleet-scale walk: 500 sessions x 8 turns
    through a 1-prefill + 2-decode fleet in conversation mode.  Warm
    TTFT stays near-flat across turn depth while a cold control (same
    prompt lengths, fresh content, no session reuse possible) grows
    linearly; affinity and provenance asserted from /metrics and
    /debug/sessions."""
    from infinistore_tpu.frontdoor import local_fleet
    from infinistore_tpu.loadgen import SessionConfig, run_sessions, \
        session_summary

    saved = {k: os.environ.get(k)
             for k in ("ISTPU_SLO_TTFT_S", "ISTPU_SLO_TPOT_S")}
    os.environ["ISTPU_SLO_TTFT_S"] = "60"
    os.environ["ISTPU_SLO_TPOT_S"] = "10"
    fd, workers, close = local_fleet(live_store, 1, 2, poll_s=0.3,
                                     n_blocks=1024)
    try:
        url = f"http://127.0.0.1:{fd.port}"
        status, _ = _post(fd.port, "/v1/completions",
                          {"prompt": [5, 5, 5, 5], "max_tokens": 2,
                           "temperature": 0})
        assert status == 200

        n_sessions, n_turns = 500, 8
        cfg = SessionConfig(
            rate=25.0, n_sessions=n_sessions, seed=42,
            turns=((1.0, n_turns),), turn_tokens=((1.0, 32),),
            system_prompt_len=64, max_tokens=1, timeout_s=600.0)
        results, _makespan = run_sessions(url, cfg)
        s = session_summary(results)
        assert s["turns"] == n_sessions * n_turns
        assert s["completed"] >= 0.98 * s["turns"], s

        # affinity from the router: re-visits overwhelmingly hit the
        # pin (no worker died), and every session's first placement was
        # a fallback
        _s, data = _get(fd.port, "/metrics")
        prom = data.decode()
        aff = {res: _metric(prom, "istpu_serve_session_affinity_total",
                            result=res) or 0.0
               for res in ("hit", "miss", "fallback")}
        assert aff["fallback"] >= 0.9 * n_sessions
        assert aff["hit"] / max(1.0, aff["hit"] + aff["miss"]) >= 0.9, aff

        # provenance + waste from every worker's session ledger: the
        # accumulated context was served from pages (local or store),
        # not recomputed — the waste fraction stays small at depth 8
        tot = {"waste": 0, "computed": 0, "reused": 0, "overlap": 0}
        for w in workers["prefill"] + workers["decode"]:
            t = _sessions_of(w.port)["totals"]
            tot["waste"] += t["waste_tokens"]
            tot["computed"] += t["computed_tokens"]
            tot["reused"] += t["reused_tokens"]
            tot["overlap"] += t["overlap_tokens"]
        assert tot["overlap"] > 0 and tot["reused"] > 0
        assert tot["waste"] <= 0.2 * max(1, tot["computed"]), tot

        # the sweep's own TTFT slope is reported (it rides queueing at
        # 25 rps, so the near-flat CONTRACT is measured below on an
        # unloaded like-for-like probe, not on this number)
        assert s["ttft_slope_ms_per_turn"] is not None

        # the cold control: the SAME per-turn prompt lengths with fresh
        # content — nothing reusable, every request pays its full
        # context, so wall time grows with depth.  Sequential and
        # unloaded; medians of 5 per depth.
        import random

        def _slope_ms(pts):
            n = len(pts)
            mx = sum(p[0] for p in pts) / n
            my = sum(p[1] for p in pts) / n
            den = sum((p[0] - mx) ** 2 for p in pts)
            return 1e3 * sum(
                (p[0] - mx) * (p[1] - my) for p in pts) / den

        crng = random.Random(7)
        cold_pts = []
        for turn in (2, 5, 8):
            length = 64 + 32 * turn
            ts = []
            for _rep in range(5):
                prompt = [crng.randrange(256) for _ in range(length)]
                t0 = time.perf_counter()
                status, _b = _post(fd.port, "/v1/completions",
                                   {"prompt": prompt, "max_tokens": 1,
                                    "temperature": 0}, timeout=600.0)
                ts.append(time.perf_counter() - t0)
                assert status == 200
            ts.sort()
            cold_pts.append((float(turn), ts[len(ts) // 2]))
        cold_slope_ms = _slope_ms(cold_pts)

        # the warm probe: the SAME sequential, unloaded measurement as
        # the control, but as real sessions with the sweep's exact
        # per-turn shapes (so every compile is already traced) — the
        # fleet holds turn N-1's pages (pinned workers + store), so
        # turn N pays only its new tokens and the wall stays near-flat
        # with depth
        wrng = random.Random(11)
        warm_by_depth = {2: [], 5: [], 8: []}
        for p in range(5):
            context = [wrng.randrange(256) for _ in range(64)]
            for turn in range(1, n_turns + 1):
                context = context + [wrng.randrange(256)
                                     for _ in range(32)]
                t0 = time.perf_counter()
                status, _b = _post(
                    fd.port, "/v1/completions",
                    {"prompt": list(context), "max_tokens": 1,
                     "temperature": 0, "session": f"probe-{p}"},
                    timeout=600.0)
                dt = time.perf_counter() - t0
                assert status == 200
                if turn in warm_by_depth:
                    warm_by_depth[turn].append(dt)
        warm_pts = []
        for turn in (2, 5, 8):
            ts = sorted(warm_by_depth[turn])
            warm_pts.append((float(turn), ts[len(ts) // 2]))
        warm_slope_ms = _slope_ms(warm_pts)

        assert cold_slope_ms > 0, cold_pts
        assert warm_slope_ms < 0.5 * cold_slope_ms, (
            f"warm {warm_slope_ms:.2f} ms/turn vs cold "
            f"{cold_slope_ms:.2f} ms/turn (warm {warm_pts}, cold "
            f"{cold_pts}, loadavg {os.getloadavg()}) — the persistence "
            f"contract is not holding at fleet scale"
        )
    finally:
        close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
