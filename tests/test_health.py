"""Fleet health plane (infinistore_tpu/health.py + doctor.py).

Pure halves first — downsampling tier roll-up, windowed reads across
tier fallback, multi-window burn-rate evaluation, the watchdog
firing/cleared state machine, ring overflow, ``?series=``/``?limit=``
handling — all under an injected clock (no sleeps, no live server).
Then the live halves: ``/debug/health`` on both planes, THE chaos-alert
acceptance walk (FaultInjector outage under live load → circuit +
burn-rate watchdogs fire and flip ``/healthz`` degraded within the fast
window, then clear after recovery — asserted from scraped ``/metrics``
+ ``/debug/health``, the PR-3 chaos pattern), and the ``istpu-doctor``
bundle whose ``SUMMARY.md`` joins a slow request to its ``step_ids``
and trace id (ledger ↔ ``/debug/engine`` ↔ stitched trace).
"""

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import tarfile
import time
import urllib.request

import pytest

from infinistore_tpu.health import (
    HealthSampler,
    TimeSeriesRing,
    WatchdogRule,
    burn_rate_rule,
    circuit_rule,
    spike_rule,
)
from infinistore_tpu.utils import metrics as m
from infinistore_tpu.utils.metrics import MetricsRegistry


# ---------------------------------------------------------------------------
# the flight recorder (pure, injected clock)
# ---------------------------------------------------------------------------


def test_ring_rollup_tiers_aggregate_correctly():
    """Raw 1 s samples roll into 10-step and 60-step buckets whose
    min/max/last/sum/count are exact."""
    r = TimeSeriesRing(step_s=1.0, clock=lambda: 0.0)
    for i in range(65):
        r.observe("v", float(i), t=float(i))
    d = r.dump("v")
    assert len(d["raw"]) == 65
    # first closed 10-step bucket covers samples 0..9
    t0, vmin, vmax, vlast, vsum, n = d["r10"][0]
    assert (t0, vmin, vmax, vlast, n) == (0.0, 0.0, 9.0, 9.0, 10)
    assert vsum == sum(range(10))
    # the 60-step tier: one closed bucket (0..59) + the open one
    t0, vmin, vmax, vlast, vsum, n = d["r60"][0]
    assert (t0, vmin, vmax, vlast, n) == (0.0, 0.0, 59.0, 59.0, 60)
    assert d["r60"][-1][0] == 60.0  # open bucket holds 60..64


def test_ring_overflow_is_fixed_memory():
    """Every tier is capacity-bounded; overflow drops the OLDEST."""
    r = TimeSeriesRing(step_s=1.0, rollups=(10,), caps=(8, 4),
                       clock=lambda: 0.0)
    for i in range(200):
        r.observe("v", float(i), t=float(i))
    d = r.dump("v")
    assert len(d["raw"]) == 8 and d["raw"][0][0] == 192.0
    assert len(d["r10"]) <= 5  # 4 closed (deque cap) + the open bucket
    # a series the recorder never saw reads as absent, not zero
    assert r.latest("nope") is None and r.delta("nope", 10) is None


def test_windowed_reads_fall_back_to_rollup_tiers():
    """delta/mean keep answering after raw history scrolled away, and a
    window predating ALL history degrades to delta-since-start."""
    r = TimeSeriesRing(step_s=1.0, rollups=(10,), caps=(5, 50),
                       clock=lambda: 0.0)
    for i in range(100):
        r.observe("c", float(i), t=float(i))
    # raw holds only 95..99; t-90 resolves through the 10-step tier
    assert r.delta("c", 90, now=99.0) == pytest.approx(90.0)
    # before everything: earliest value (0.0) stands in
    assert r.value_at("c", -50.0) == 0.0
    assert r.delta("c", 10_000, now=99.0) == pytest.approx(99.0)
    # the window is inclusive at its left edge: [95, 99] -> mean 97
    assert r.mean("c", 4, now=99.0) == pytest.approx(97.0)


def test_changes_and_slope():
    r = TimeSeriesRing(step_s=1.0, clock=lambda: 0.0)
    for i, v in enumerate([0, 0, 1, 1, 2, 0, 0]):
        r.observe("state", float(v), t=float(i))
    assert r.changes("state", 100, now=6.0) == 3  # 0->1, 1->2, 2->0
    for i in range(10):
        r.observe("mem", 100.0 + 10.0 * i, t=10.0 + i)
    assert r.slope("mem", 100, now=19.0) == pytest.approx(10.0)


# ---------------------------------------------------------------------------
# burn-rate math (pure)
# ---------------------------------------------------------------------------


def _feed(r, t, finished, viol):
    r.observe("fin", float(finished), t=t)
    r.observe("viol", float(viol), t=t)


def test_burn_rate_requires_both_windows():
    """An OLD burst (outside the fast window) must not fire even though
    the slow window still burns; a live sustained burn fires; recovery
    clears as soon as the fast window is clean."""
    rule = burn_rate_rule("b", "viol", "fin", fast_s=10, slow_s=60)
    r = TimeSeriesRing(step_s=1.0, clock=lambda: 0.0)
    # t=0..9: a violation burst (every request misses)
    fin = viol = 0
    for t in range(10):
        fin += 2
        viol += 2
        _feed(r, float(t), fin, viol)
    assert rule.check(r, 9.0) is not None  # live burst: both windows burn
    # t=10..39: healthy traffic; the burst ages out of the fast window
    for t in range(10, 40):
        fin += 2
        _feed(r, float(t), fin, viol)
    res = rule.check(r, 39.0)
    assert res is None, res  # slow window still remembers; fast is clean
    # no traffic at all -> nothing is burning (never fire on silence)
    r2 = TimeSeriesRing(step_s=1.0, clock=lambda: 0.0)
    assert rule.check(r2, 50.0) is None


def test_burn_rate_threshold_and_budget_math():
    """burn = (violations/finished)/budget per window; both ≥ threshold
    fires, reported value = min(fast, slow)."""
    rule = burn_rate_rule("b", "viol", "fin", slo_frac=0.1,
                          threshold=2.0, fast_s=10, slow_s=10)
    r = TimeSeriesRing(step_s=1.0, clock=lambda: 0.0)
    fin = viol = 0
    for t in range(10):
        fin += 10
        viol += 3  # 30% violating = 3.0x the 10% budget
        _feed(r, float(t), fin, viol)
    res = rule.check(r, 9.0)
    assert res is not None and res["value"] == pytest.approx(3.0, rel=0.2)
    # 15% violating = 1.5x budget: under the 2x threshold
    r2 = TimeSeriesRing(step_s=1.0, clock=lambda: 0.0)
    fin = viol = 0
    for t in range(10):
        fin += 20
        viol += 3
        _feed(r2, float(t), fin, viol)
    assert rule.check(r2, 9.0) is None


def test_circuit_rule_open_and_flap():
    rule = circuit_rule(flap_n=4, flap_window_s=100)
    r = TimeSeriesRing(step_s=1.0, clock=lambda: 0.0)
    for t in range(5):
        r.observe("store.circuit", 0.0, t=float(t))
    assert rule.check(r, 4.0) is None
    r.observe("store.circuit", 1.0, t=5.0)  # open
    res = rule.check(r, 5.0)
    assert res is not None and "open" in res["reason"]
    # one outage cycle (closed->open->half-open->closed = 3 changes)
    # is recovery, not flapping...
    r.observe("store.circuit", 2.0, t=6.0)
    r.observe("store.circuit", 0.0, t=7.0)
    assert rule.check(r, 7.0) is None
    # ...a second cycle inside the window IS flapping
    r.observe("store.circuit", 1.0, t=8.0)
    r.observe("store.circuit", 0.0, t=9.0)
    res = rule.check(r, 9.0)
    assert res is not None and "flapped" in res["reason"]


# ---------------------------------------------------------------------------
# the sampler + watchdog state machine (pure, injected clock)
# ---------------------------------------------------------------------------


def test_sampler_fire_clear_transitions_and_metrics():
    """Probes feed the ring, rules fire and clear with hysteresis, the
    istpu_health_* families track transitions, and the snapshot carries
    fired counts + peak values."""
    now = [0.0]
    state = {"viol": 0.0, "fin": 0.0}
    reg = MetricsRegistry()
    sampler = HealthSampler(
        probes={"fin": lambda: state["fin"],
                "viol": lambda: state["viol"],
                "boom": lambda: 1 / 0},  # a raising probe is skipped
        rules=[burn_rate_rule("burn", "viol", "fin",
                              fast_s=5, slow_s=20),
               spike_rule("spike", "viol", threshold=100, window_s=5)],
        metrics=reg, clock=lambda: now[0], enabled=True, step_s=1.0,
    )
    for i in range(5):  # healthy traffic
        now[0] = float(i)
        state["fin"] += 10
        sampler.tick()
    assert sampler.firing() == [] and sampler.probe_errors >= 5
    for i in range(5, 10):  # every request violates
        now[0] = float(i)
        state["fin"] += 10
        state["viol"] += 10
        sampler.tick()
    firing = sampler.firing()
    assert [f["rule"] for f in firing] == ["burn"]
    assert firing[0]["severity"] == "page" and sampler.page_firing()
    text = reg.to_prometheus_text()
    assert 'istpu_health_alert_active{rule="burn"} 1' in text
    assert ('istpu_health_alerts_total{rule="burn",severity="page"} 1'
            in text)
    # recovery: healthy fast window clears it
    for i in range(10, 18):
        now[0] = float(i)
        state["fin"] += 10
        sampler.tick()
    assert sampler.firing() == [] and not sampler.page_firing()
    assert 'istpu_health_alert_active{rule="burn"} 0' in \
        reg.to_prometheus_text()
    snap = sampler.snapshot()
    assert snap["alerts"]["burn"]["fired"] == 1
    assert snap["alerts"]["burn"]["cleared"] == 1
    assert snap["alerts"]["burn"]["peak"] >= 2.0
    assert snap["alerts_fired"] == 1
    tos = [t["to"] for t in snap["transitions"]
           if t["rule"] == "burn"]
    assert tos == ["firing", "cleared"]


def test_clear_hysteresis_holds_until_clear_for_s():
    now = [0.0]
    bad = [True]
    rule = WatchdogRule(
        "r", "warn",
        check=lambda ring, t: {"reason": "x"} if bad[0] else None,
        clear_for_s=5.0,
    )
    sampler = HealthSampler(probes={}, rules=[rule],
                            metrics=MetricsRegistry(),
                            clock=lambda: now[0], enabled=True)
    sampler.tick()
    assert [f["rule"] for f in sampler.firing()] == ["r"]
    bad[0] = False
    for t in (1.0, 3.0, 4.9):
        now[0] = t
        sampler.tick()
        assert sampler.firing(), "must hold through the hysteresis window"
    now[0] = 6.0
    sampler.tick()
    assert sampler.firing() == []


def test_snapshot_series_limit_and_kill_switch(monkeypatch):
    now = [0.0]
    sampler = HealthSampler(probes={"a": lambda: now[0],
                                    "b": lambda: 1.0},
                            metrics=MetricsRegistry(),
                            clock=lambda: now[0], enabled=True)
    for i in range(30):
        now[0] = float(i)
        sampler.tick()
    snap = sampler.snapshot(series="a,b", limit=5)
    assert set(snap["timeline"]) == {"a", "b"}
    assert len(snap["timeline"]["a"]) == 5
    assert snap["timeline"]["a"][-1][1] == 29.0
    assert "a" in snap["series"] and snap["ticks"] == 30
    # no series asked for -> no timeline key (alerts stay cheap to poll)
    assert "timeline" not in sampler.snapshot()
    # kill switch: the sampler is inert and says so
    monkeypatch.setenv("ISTPU_HEALTH", "0")
    off = HealthSampler(probes={"a": lambda: 1.0},
                        metrics=MetricsRegistry())
    assert off.enabled is False
    off.tick()
    off.start()
    assert off.snapshot() == {"enabled": False} and off.ticks == 0
    assert off._thread is None


def test_env_knobs(monkeypatch):
    monkeypatch.setenv("ISTPU_HEALTH_STEP_S", "0.5")
    monkeypatch.setenv("ISTPU_BURN_FAST_S", "7")
    monkeypatch.setenv("ISTPU_BURN_SLOW_S", "77")
    sampler = HealthSampler(probes={}, metrics=MetricsRegistry())
    assert sampler.step_s == 0.5
    from infinistore_tpu.health import burn_windows

    assert burn_windows() == (7.0, 77.0)


# ---------------------------------------------------------------------------
# doctor summary (pure)
# ---------------------------------------------------------------------------


def _plane(url, entries):
    out = {"url": url}
    for name, path, fname, payload in entries:
        data = json.dumps(payload).encode() if payload is not None else None
        out[name] = {"path": path, "file": fname, "ok": data is not None,
                     "error": None if data is not None else "unreachable",
                     "bytes": len(data or b""), "data": data}
    return out


def test_doctor_summary_joins_requests_to_steps(tmp_path):
    """summarize_capture joins the slowest ledger record to its step
    records and trace id, and write_bundle round-trips through the
    tarball with a manifest that names every capture."""
    from infinistore_tpu.doctor import (
        SERVE_ENDPOINTS,
        STORE_ENDPOINTS,
        summarize_capture,
        write_bundle,
    )

    requests = {"records": [
        {"req_id": 7, "lane": "0", "outcome": "done", "e2e_s": 1.75,
         "ttft_s": 1.2, "trace_id": "abcd-42", "step_ids": [11, 12],
         "shares": {"queue": 0.1, "store": 0.0, "prefill": 0.6,
                    "decode": 0.3}},
        {"req_id": 8, "lane": "0", "outcome": "done", "e2e_s": 0.01,
         "ttft_s": 0.005, "trace_id": "abcd-50", "step_ids": [13]},
    ]}
    engine = {
        "records": [
            {"step": 11, "kind": "prefill", "dur_s": 1.1,
             "dispatches": {"prefill": 4}, "tokens": 0,
             "host_stall_s": 0.4},
            {"step": 12, "kind": "decode", "dur_s": 0.5,
             "dispatches": {"decode": 2}, "tokens": 8},
        ],
        "summary": {"steps": 12, "host_stall_frac": 0.3,
                    "retraces_per_100_steps": 8.0,
                    "retraces": {"decode_many": 3, "prefill_forward": 1}},
    }
    health = {"enabled": True, "firing": ["ttft_burn"],
              "alerts_fired": 2,
              "alerts": {"ttft_burn": {"severity": "page",
                                       "reason": "burning 5x"}}}
    admission = {
        "enabled": True, "mode": "shed",
        "burn": {"value": 5.0, "shed_lanes": ["0"]},
        "shed_by_reason": {"burn": {"0": 7}, "quota": {"3": 2}},
        "shed_total": 7,
        "quota": {"tenants": {"3": {"rate_toks_per_s": 100.0,
                                    "burst_tokens": 200.0,
                                    "available": -5.0, "used_frac": 1.0,
                                    "throttled": 2}},
                  "throttled_total": 2},
        "prefill_throttle": {"active": True, "budget_tokens": 64},
    }
    serve_payloads = {
        "/metrics": None, "/healthz": {"status": "degraded"},
        "/debug/requests": requests, "/debug/engine": engine,
        "/debug/traces": {"traceEvents": []},
        "/debug/cluster": {"enabled": False}, "/debug/health": health,
        "/debug/admission": admission,
    }
    cap = {
        "fetched_at": 1754000000.0,
        "serve": _plane("http://s:8000", [
            # .get: endpoints added later (e.g. /debug/fleet) render as
            # unreachable here — the summary must degrade per endpoint
            (name, path, fname, serve_payloads.get(path))
            for name, path, fname in SERVE_ENDPOINTS
        ]),
        "stores": [_plane("http://st:18080", [
            (name, path, fname, None)  # fully unreachable node
            for name, path, fname in STORE_ENDPOINTS
        ])],
    }
    text = summarize_capture(cap)
    # the join: the slowest request, its trace id, its step ids, and the
    # per-step engine records under it
    assert "req 7" in text and "trace_id abcd-42" in text
    assert "step_ids 11,12" in text
    assert "step 11: kind=prefill" in text and "host_stall 0.400s" in text
    assert "step 12: kind=decode" in text
    assert "**ttft_burn** [page]" in text and "burning 5x" in text
    assert "decode_many: 3" in text
    assert "UNREACHABLE" in text  # the dead store degrades, not fails
    # the admission plane's state sits next to the alerts it reacts to
    assert "Admission / overload control" in text
    assert "SHEDDING lanes 0" in text
    assert "shed[burn]: 7 (lane 0: 7)" in text
    assert "quota tenant 3" in text and "throttled 2" in text
    assert "prefill throttle ACTIVE (64 tok/step)" in text
    out = tmp_path / "bundle.tar.gz"
    manifest = write_bundle(cap, str(out))
    with tarfile.open(out) as tar:
        names = set(tar.getnames())
        assert {"SUMMARY.md", "manifest.json"} <= names
        assert "serve/debug_requests.json" in names
        back = tar.extractfile("SUMMARY.md").read().decode()
    assert back == text
    assert manifest["stores"][0]["endpoints"][0]["ok"] is False


def test_doctor_summary_answers_did_any_stream_die():
    """The router-merged capture (/debug/fleet?merged=1) feeds a
    'Streams — did any die?' section: every replica's reachability plus
    the fleet-summed splice ledger, with a three-way verdict (nothing
    died / died-but-resumed / LOST)."""
    from infinistore_tpu.doctor import SERVE_ENDPOINTS, summarize_capture

    def cap_for(stream):
        merged = {
            "enabled": True, "role": "router-fleet",
            "replicas": 2, "reachable": 1,
            "routers": [
                {"endpoint": "127.0.0.1:9000", "self": True,
                 "reachable": True, "report": {}},
                {"endpoint": "127.0.0.1:9001", "self": False,
                 "reachable": False, "report": None},
            ],
            "requests": {"2xx": 20.0, "4xx": 0.0, "5xx": 0.0,
                         "error": 0.0},
            "stream": stream,
        }
        payloads = {"/debug/fleet?merged=1": merged}
        return {"fetched_at": 1754000000.0, "stores": [],
                "serve": _plane("http://s:8000", [
                    (name, path, fname, payloads.get(path))
                    for name, path, fname in SERVE_ENDPOINTS
                ])}

    quiet = summarize_capture(cap_for(
        {"aborts": 0.0, "resumes_ok": 0.0, "resumes_failed": 0.0}))
    assert "Streams — did any die?" in quiet
    assert "router replicas: 1/2 reachable" in quiet
    assert "**UNREACHABLE**" in quiet  # the dead peer is named
    assert "no: zero aborts, zero resumes" in quiet

    spliced = summarize_capture(cap_for(
        {"aborts": 0.0, "resumes_ok": 3.0, "resumes_failed": 0.0}))
    assert "streams died but none were lost: 3" in spliced

    lost = summarize_capture(cap_for(
        {"aborts": 2.0, "resumes_ok": 1.0, "resumes_failed": 2.0}))
    assert "**YES — streams were LOST**" in lost
    assert "2 resume failure(s), 2 client-visible abort(s)" in lost


# ---------------------------------------------------------------------------
# live halves: serve + store planes, the chaos walk, the doctor bundle
# ---------------------------------------------------------------------------

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import infinistore_tpu as ist  # noqa: E402
from infinistore_tpu.engine import InferenceEngine  # noqa: E402
from infinistore_tpu.kv import PagedCacheConfig  # noqa: E402
from infinistore_tpu.models import TINY, init_params, scaled  # noqa: E402
from infinistore_tpu.serve import ServingServer  # noqa: E402

CFG = scaled(TINY, dtype=jnp.float32)
PARAMS = init_params(CFG, jax.random.PRNGKey(7))
T = 4
PROMPT = [11, 42, 7, 99, 5, 3, 17, 28, 64, 1, 2]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _boot(port, mport, extra_env=None):
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", "python"],
        env={**os.environ, "JAX_PLATFORMS": "cpu", **(extra_env or {})},
    )
    deadline = time.time() + 25
    for p in (port, mport):
        while True:
            if proc.poll() is not None:
                pytest.fail("server process failed to start")
            try:
                socket.create_connection(("127.0.0.1", p),
                                         timeout=0.5).close()
                break
            except OSError:
                if time.time() >= deadline:
                    proc.kill()
                    pytest.fail(f"server port {p} did not come up")
                time.sleep(0.1)
    return proc


def _stop(proc):
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def _arm(mport, rules):
    req = urllib.request.Request(
        f"http://127.0.0.1:{mport}/faults", method="POST",
        data=json.dumps(rules).encode(),
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.load(r)


def _post(port, body, timeout=180, path="/v1/completions"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data)


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def make_pc(n_blocks=128):
    return PagedCacheConfig(
        n_layers=CFG.n_layers, n_kv_heads=CFG.n_kv_heads,
        head_dim=CFG.head_dim, n_blocks=n_blocks, block_tokens=T,
        dtype=CFG.dtype,
    )


HEALTH_ENV = {
    # tight windows so the chaos walk fires and clears in test time:
    # 0.2 s sampling, 3 s fast / 15 s slow burn windows
    "ISTPU_HEALTH_STEP_S": "0.2",
    "ISTPU_BURN_FAST_S": "3",
    "ISTPU_BURN_SLOW_S": "15",
    # this walk tests DETECTION (the watchdogs firing/clearing) — the
    # admission controller ACTING on the same burn would shed the
    # induced overload with 429s and change what the walk observes;
    # the acting side has its own chaos walk in tests/test_admission.py
    "ISTPU_ADMISSION": "0",
}


@pytest.fixture(scope="module")
def health_stack():
    """A serving server (tight SLO, fast health windows) attached to a
    dedicated store subprocess whose manage endpoint is registered for
    the cluster rollup — the stack the chaos walk and the doctor run
    against."""
    old = {k: os.environ.get(k) for k in HEALTH_ENV}
    os.environ.update(HEALTH_ENV)
    port, mport = _free_port(), _free_port()
    proc = _boot(port, mport, extra_env=HEALTH_ENV)
    conn = ist.InfinityConnection(ist.ClientConfig(
        host_addr="127.0.0.1", service_port=port,
        connection_type=ist.TYPE_SHM, op_timeout_s=0.6,
        log_level="error",
    ))
    conn.connect()
    eng = InferenceEngine(
        PARAMS, CFG, make_pc(), conn=conn, model_id="health-serve",
        store_durability="relaxed",
    )
    eng.decode_chunk = 4
    eng.breaker.failure_threshold = 2
    eng.breaker.cooldown_s = 1.0
    srv = ServingServer(
        eng, port=0, max_batch=4, model_id="health-serve",
        slo_ttft_s=0.3,
        store_manage_endpoints=[f"127.0.0.1:{mport}"],
    )
    srv.start()
    yield srv, proc, port, mport
    srv.close()
    conn.close()
    _stop(proc)
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _health(srv_port):
    st, data = _get(srv_port, "/debug/health")
    assert st == 200
    return json.loads(data)


def _wait_firing(srv_port, rule, want=True, deadline_s=15.0,
                 tick=None):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        h = _health(srv_port)
        if (rule in h.get("firing", [])) == want:
            return h
        if tick is not None:
            tick()
        time.sleep(0.2)
    return _health(srv_port)


def test_serve_debug_health_live(health_stack):
    """The serving /debug/health: sampler running, series recorded,
    ?series=/?limit= honored, the cluster rollup reaches the store's
    manage plane, and the istpu_health_* families are on /metrics."""
    srv, _proc, _port, mport = health_stack
    n = [0]

    def ask():
        p = [60 + n[0]] + PROMPT[1:]
        n[0] += 1
        st, body = _post(srv.port, {"prompt": p, "max_tokens": 4,
                                    "temperature": 0})
        assert st == 200, body

    ask()
    time.sleep(0.8)  # a few sampler ticks
    h = _health(srv.port)
    assert h["enabled"] and h["ticks"] >= 2
    assert "serve.finished" in h["series"]
    assert {"ttft_burn", "tpot_burn", "circuit_flap",
            "streamer_stall"} <= set(h["alerts"])
    st, data = _get(srv.port,
                    "/debug/health?series=serve.finished&limit=3")
    tl = json.loads(data)["timeline"]["serve.finished"]
    assert 1 <= len(tl) <= 3
    # cluster rollup polled the store's manage plane
    assert h["cluster"]["nodes"][0]["endpoint"] == f"127.0.0.1:{mport}"
    assert h["cluster"]["nodes"][0]["reachable"] is True
    # store-side plane answers too
    raw = urllib.request.urlopen(
        f"http://127.0.0.1:{mport}/debug/health?series=store.usage&limit=2",
        timeout=10,
    ).read()
    sh = json.loads(raw)
    assert sh["enabled"] and "store.usage" in sh["series"]
    assert "pool_pressure" in sh["alerts"]
    assert len(sh["timeline"]["store.usage"]) <= 2
    # metric families on both expositions
    st, data = _get(srv.port, "/metrics")
    assert b"istpu_health_alert_active" in data
    assert b"istpu_health_sampler_lag_seconds" in data
    mtext = urllib.request.urlopen(
        f"http://127.0.0.1:{mport}/metrics", timeout=10).read()
    assert b"istpu_health_alert_active" in mtext


def test_chaos_outage_fires_burn_and_circuit_then_clears(health_stack):
    """THE acceptance chaos walk: a FaultInjector store outage under
    live load makes the burn-rate and circuit watchdogs fire in
    /debug/health and flip /healthz degraded within the fast window,
    then clear after recovery — asserted from scraped /metrics +
    /debug/health."""
    srv, _proc, _port, mport = health_stack
    n = [100]

    def ask():
        p = [50 + n[0] % 400] + PROMPT[1:]
        n[0] += 1
        st, body = _post(srv.port, {"prompt": p, "max_tokens": 4,
                                    "temperature": 0})
        assert st == 200, body

    # phase 0: healthy traffic, then let the first-compile TTFT blip age
    # out of the 3 s fast window so the baseline is clean
    for _ in range(3):
        ask()
    h = _wait_firing(srv.port, "ttft_burn", want=False, deadline_s=10)
    assert "ttft_burn" not in h["firing"], h["alerts"]["ttft_burn"]
    st, data = _get(srv.port, "/healthz")
    assert json.loads(data)["status"] == "ok", data

    # phase 1: the store answers LATE (0.45 s per op — an outage that
    # breaks the SLO without tripping the breaker): every request's
    # lookup drags TTFT past the 0.3 s target -> burn-rate fires
    _arm(mport, [{"op": "*", "action": "delay", "delay_s": 0.45}])
    for _ in range(6):
        ask()
    h = _wait_firing(srv.port, "ttft_burn", want=True, deadline_s=10,
                     tick=ask)
    assert "ttft_burn" in h["firing"], h["alerts"]["ttft_burn"]
    burn = h["alerts"]["ttft_burn"]
    assert burn["severity"] == "page" and burn["peak"] >= 2.0

    # a firing page alert flips /healthz degraded (the circuit is still
    # CLOSED — this degradation is the health plane's own verdict)
    st, data = _get(srv.port, "/healthz")
    hz = json.loads(data)
    assert hz["status"] == "degraded", hz
    assert "ttft_burn" in hz["alerts"]["rules"], hz
    assert hz.get("store_circuit", "closed") == "closed", hz

    # phase 2: the store HANGS -> breaker opens -> the circuit watchdog
    # fires on the state the sampler recorded
    _arm(mport, [{"op": "*", "action": "stall"}])
    for _ in range(3):
        ask()  # completes via recompute; failures feed the breaker
    deadline = time.time() + 10
    while srv.engine.breaker.state != "open" and time.time() < deadline:
        ask()
        time.sleep(0.05)
    assert srv.engine.breaker.state == "open"
    h = _wait_firing(srv.port, "circuit_flap", want=True, deadline_s=10)
    assert "circuit_flap" in h["firing"], h["alerts"]["circuit_flap"]
    assert "open" in h["alerts"]["circuit_flap"]["reason"]

    # the whole verdict is scrapeable from /metrics (the PR-3 pattern)
    st, data = _get(srv.port, "/metrics")
    parsed = m.parse_prometheus_text(data.decode())
    assert parsed.get(("istpu_health_alert_active",
                       (("rule", "ttft_burn"),))) == 1.0
    assert parsed.get(("istpu_health_alert_active",
                       (("rule", "circuit_flap"),))) == 1.0
    assert parsed.get(("istpu_health_alerts_total",
                       (("rule", "ttft_burn"),
                        ("severity", "page")))) >= 1.0

    # phase 3: recovery — faults cleared, the circuit closes on the
    # half-open probe, healthy traffic flushes the fast window, and
    # every watchdog clears
    _arm(mport, [])
    time.sleep(srv.engine.breaker.cooldown_s + 0.1)
    deadline = time.time() + 30
    while srv.engine.breaker.state != "closed" and time.time() < deadline:
        ask()
        time.sleep(0.05)
    assert srv.engine.breaker.state == "closed"
    h = _wait_firing(srv.port, "ttft_burn", want=False, deadline_s=25,
                     tick=ask)
    assert "ttft_burn" not in h["firing"], h["alerts"]["ttft_burn"]
    # the flap branch may truthfully hold while the outage's state
    # changes are still inside its window (5x fast = 15 s here); it must
    # age out and clear well inside the deadline
    h = _wait_firing(srv.port, "circuit_flap", want=False, deadline_s=30)
    assert "circuit_flap" not in h["firing"], h["alerts"]["circuit_flap"]
    # fired AND cleared transitions are on the record
    tos = {(t["rule"], t["to"]) for t in h["transitions"]}
    assert ("ttft_burn", "firing") in tos and ("ttft_burn", "cleared") in tos
    deadline = time.time() + 20
    while time.time() < deadline:
        st, data = _get(srv.port, "/healthz")
        hz = json.loads(data)
        if hz["status"] == "ok":
            break
        time.sleep(0.3)
    assert hz["status"] == "ok", hz
    assert parsed.get(("istpu_health_alerts_total",
                       (("rule", "circuit_flap"),
                        ("severity", "page")))) >= 1.0


def test_doctor_bundle_joins_slow_request_to_steps(health_stack,
                                                   tmp_path):
    """THE doctor acceptance: one istpu-doctor invocation against the
    live serve (+store, auto-discovered from the cluster rollup)
    produces a bundle whose SUMMARY.md joins at least one slow request
    to its step_ids and trace id — read back from the tarball."""
    from infinistore_tpu import doctor

    srv, _proc, _port, mport = health_stack
    for i in range(3):
        st, body = _post(srv.port, {"prompt": [200 + i] + PROMPT[1:],
                                    "max_tokens": 6, "temperature": 0})
        assert st == 200, body
    time.sleep(0.6)  # sampler ticks + ledger settles
    out = tmp_path / "incident.tar.gz"
    rc = doctor.main(["--serve-url", f"http://127.0.0.1:{srv.port}",
                      "--out", str(out)])
    assert rc == 0 and out.exists()
    with tarfile.open(out) as tar:
        names = set(tar.getnames())
        summary = tar.extractfile("SUMMARY.md").read().decode()
        manifest = json.load(tar.extractfile("manifest.json"))
        requests = json.load(tar.extractfile("serve/debug_requests.json"))
        engine = json.load(tar.extractfile("serve/debug_engine.json"))
    # the store's manage plane was DISCOVERED from the serve rollup
    assert any(name.startswith("store-0/") for name in names), names
    assert "serve/debug_health.json" in names
    assert manifest["stores"][0]["url"].endswith(str(mport))
    # the join, asserted against the live payloads: the slowest ledger
    # record's trace id and step ids all appear in SUMMARY.md, and its
    # steps resolve in the captured /debug/engine ring
    recs = [r for r in requests["records"] if r.get("e2e_s") is not None]
    assert recs, requests
    slowest = max(recs, key=lambda r: r["e2e_s"])
    assert slowest["trace_id"] and slowest["step_ids"], slowest
    assert f"trace_id {slowest['trace_id']}" in summary
    joined = ",".join(str(s) for s in slowest["step_ids"])
    assert f"step_ids {joined}" in summary
    known_steps = {r.get("step") for r in engine["records"]}
    assert set(slowest["step_ids"][-3:]) & known_steps
    for sid in slowest["step_ids"][-3:]:
        if sid in known_steps:
            assert f"step {sid}:" in summary
    # per-endpoint manifest entries say what was (and wasn't) captured
    serve_ok = {e["endpoint"]: e["ok"]
                for e in manifest["serve"]["endpoints"]}
    assert serve_ok["/debug/requests"] and serve_ok["/debug/health"]
