"""HF checkpoint import: converted weights must reproduce transformers'
Llama logits (validates weight orientation, GQA mapping, the RoPE-convention
permutation, RMSNorm placement, and tied embeddings)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from infinistore_tpu.models.hf import config_from_hf, params_from_hf  # noqa: E402
from infinistore_tpu.models.llama import prefill_forward  # noqa: E402


def make_hf_model(tie: bool):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=tie,
        attention_bias=False,
        mlp_bias=False,
    )
    torch.manual_seed(0)
    with torch.no_grad():
        model = transformers.LlamaForCausalLM(hf_cfg)
        # random init is near-zero-logit; scale up so differences are visible
        for p in model.parameters():
            p.mul_(3.0)
    model.eval()
    return model


@pytest.mark.parametrize("tie", [False, True])
def test_logits_match_transformers(tie):
    model = make_hf_model(tie)
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    params = params_from_hf(model, cfg)

    tokens = np.array([[5, 17, 99, 3, 42, 200, 7, 1]], dtype=np.int64)
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()

    got, _ = prefill_forward(params, cfg, jnp.asarray(tokens, dtype=jnp.int32))
    got = np.asarray(got, dtype=np.float32)

    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_llama31_rope_scaling_matches_transformers():
    """Llama-3.1/3.2 checkpoints ship rope_scaling rope_type='llama3'; the
    imported model must reproduce HF logits with the scaled frequencies."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=500000.0,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 32,
        },
    )
    torch.manual_seed(1)
    with torch.no_grad():
        model = transformers.LlamaForCausalLM(hf_cfg)
        for p in model.parameters():
            p.mul_(3.0)
    model.eval()
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    assert cfg.rope_scaling == (8.0, 1.0, 4.0, 32)
    params = params_from_hf(model, cfg)

    # positions past original_max_position_embeddings exercise the remap
    tokens = np.arange(1, 49, dtype=np.int64)[None] % 256
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()
    got, _ = prefill_forward(params, cfg, jnp.asarray(tokens, dtype=jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got, np.float32), want, rtol=2e-3, atol=2e-3
    )


def test_rejects_unrepresentable_configs():
    base = dict(
        vocab_size=64, hidden_size=64, intermediate_size=128,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=2,
    )
    with pytest.raises(ValueError, match="head_dim"):
        config_from_hf(transformers.LlamaConfig(**base, head_dim=32))
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(transformers.LlamaConfig(
            **base,
            rope_scaling={"rope_type": "yarn", "factor": 2.0},
        ))


def test_state_dict_entry_point():
    model = make_hf_model(tie=False)
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    params = params_from_hf(model.state_dict(), cfg)
    tokens = jnp.asarray([[1, 2, 3]], dtype=jnp.int32)
    logits, kv = prefill_forward(params, cfg, tokens)
    assert logits.shape == (1, 3, cfg.vocab_size)
    assert kv.shape[0] == cfg.n_layers
