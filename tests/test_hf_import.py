"""HF checkpoint import: converted weights must reproduce transformers'
Llama logits (validates weight orientation, GQA mapping, the RoPE-convention
permutation, RMSNorm placement, and tied embeddings)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from dataclasses import replace as dataclasses_replace  # noqa: E402

from infinistore_tpu.models.hf import config_from_hf, params_from_hf  # noqa: E402
from infinistore_tpu.models.llama import prefill_forward  # noqa: E402


def make_hf_model(tie: bool):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=tie,
        attention_bias=False,
        mlp_bias=False,
    )
    torch.manual_seed(0)
    with torch.no_grad():
        model = transformers.LlamaForCausalLM(hf_cfg)
        # random init is near-zero-logit; scale up so differences are visible
        for p in model.parameters():
            p.mul_(3.0)
    model.eval()
    return model


@pytest.mark.parametrize("tie", [False, True])
def test_logits_match_transformers(tie):
    model = make_hf_model(tie)
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    params = params_from_hf(model, cfg)

    tokens = np.array([[5, 17, 99, 3, 42, 200, 7, 1]], dtype=np.int64)
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()

    got, _ = prefill_forward(params, cfg, jnp.asarray(tokens, dtype=jnp.int32))
    got = np.asarray(got, dtype=np.float32)

    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_llama31_rope_scaling_matches_transformers():
    """Llama-3.1/3.2 checkpoints ship rope_scaling rope_type='llama3'; the
    imported model must reproduce HF logits with the scaled frequencies."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=500000.0,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 32,
        },
    )
    torch.manual_seed(1)
    with torch.no_grad():
        model = transformers.LlamaForCausalLM(hf_cfg)
        for p in model.parameters():
            p.mul_(3.0)
    model.eval()
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    assert cfg.rope_scaling == (8.0, 1.0, 4.0, 32)
    params = params_from_hf(model, cfg)

    # positions past original_max_position_embeddings exercise the remap
    tokens = np.arange(1, 49, dtype=np.int64)[None] % 256
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()
    got, _ = prefill_forward(params, cfg, jnp.asarray(tokens, dtype=jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got, np.float32), want, rtol=2e-3, atol=2e-3
    )


def test_rejects_unrepresentable_configs():
    base = dict(
        vocab_size=64, hidden_size=64, intermediate_size=128,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=2,
    )
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(transformers.LlamaConfig(
            **base,
            rope_scaling={"rope_type": "yarn", "factor": 2.0},
        ))
    with pytest.raises(ValueError, match="model_type"):
        config_from_hf(transformers.GemmaConfig(**base))
    with pytest.raises(ValueError, match="max_window_layers"):
        config_from_hf(transformers.Qwen2Config(
            **{**base, "num_hidden_layers": 4}, use_sliding_window=True,
            sliding_window=8, max_window_layers=2,
        ))
    # HF windows layers >= max_window_layers: mwl >= n_layers means NO
    # layer is windowed; mwl == 0 means uniformly windowed
    cfg_full = config_from_hf(transformers.Qwen2Config(
        **{**base, "num_hidden_layers": 4}, use_sliding_window=True,
        sliding_window=8, max_window_layers=4,
    ))
    assert cfg_full.sliding_window is None
    cfg_win = config_from_hf(transformers.Qwen2Config(
        **{**base, "num_hidden_layers": 4}, use_sliding_window=True,
        sliding_window=8, max_window_layers=0,
    ))
    assert cfg_win.sliding_window == 8
    # a decoupled head_dim is supported, not rejected
    cfg = config_from_hf(transformers.LlamaConfig(**base, head_dim=32))
    assert cfg.head_dim == 32


def test_mistral_sliding_window_logits_match():
    """Mistral = Llama machinery + sliding-window attention.  A tiny window
    (5) over a longer sequence (14) makes the windowed and full-causal
    outputs diverge, so this fails if the mask is wrong in either
    direction."""
    hf_cfg = transformers.MistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        sliding_window=5, tie_word_embeddings=False,
    )
    torch.manual_seed(2)
    with torch.no_grad():
        model = transformers.MistralForCausalLM(hf_cfg)
        for p in model.parameters():
            p.mul_(3.0)
    model.eval()
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    assert cfg.sliding_window == 5
    params = params_from_hf(model, cfg)

    tokens = np.arange(3, 45, 3, dtype=np.int64)[None] % 256  # len 14 > window
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()
    got, _ = prefill_forward(params, cfg, jnp.asarray(tokens, dtype=jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got, np.float32), want, rtol=2e-3, atol=2e-3
    )
    # sanity: the window actually bites (full-causal differs at the tail)
    full, _ = prefill_forward(
        params, dataclasses_replace(cfg, sliding_window=None),
        jnp.asarray(tokens, dtype=jnp.int32),
    )
    assert not np.allclose(np.asarray(full, np.float32)[0, -1], want[0, -1],
                           rtol=2e-3, atol=2e-3)


def test_qwen2_bias_logits_match():
    """Qwen2/2.5 = Llama machinery + QKV biases (with the RoPE permutation
    applied to the q/k bias rows)."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-6, rope_theta=1e6,
        tie_word_embeddings=False,
    )
    torch.manual_seed(3)
    with torch.no_grad():
        model = transformers.Qwen2ForCausalLM(hf_cfg)
        for p in model.parameters():
            p.mul_(2.0)
    model.eval()
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    assert cfg.attn_bias and cfg.sliding_window is None
    params = params_from_hf(model, cfg)
    assert "bq" in params["layers"]

    tokens = np.array([[7, 3, 99, 250, 12, 1, 88, 41, 5]], dtype=np.int64)
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()
    got, _ = prefill_forward(params, cfg, jnp.asarray(tokens, dtype=jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got, np.float32), want, rtol=2e-3, atol=2e-3
    )


def test_qwen3_qk_norm_logits_match():
    """Qwen3 = Llama machinery + per-head Q/K RMSNorm and a head_dim
    decoupled from hidden/heads (8 != 64/4)."""
    hf_cfg = transformers.Qwen3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=128, rms_norm_eps=1e-6,
        rope_theta=1e6, tie_word_embeddings=False,
    )
    torch.manual_seed(4)
    with torch.no_grad():
        model = transformers.Qwen3ForCausalLM(hf_cfg)
        for p in model.parameters():
            p.mul_(2.0)
    model.eval()
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    assert cfg.qk_norm and cfg.head_dim == 8
    params = params_from_hf(model, cfg)
    assert "q_norm" in params["layers"]

    tokens = np.array([[5, 100, 2, 43, 17, 200, 9]], dtype=np.int64)
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()
    got, _ = prefill_forward(params, cfg, jnp.asarray(tokens, dtype=jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got, np.float32), want, rtol=2e-3, atol=2e-3
    )


def test_state_dict_entry_point():
    model = make_hf_model(tie=False)
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    params = params_from_hf(model.state_dict(), cfg)
    tokens = jnp.asarray([[1, 2, 3]], dtype=jnp.int32)
    logits, kv = prefill_forward(params, cfg, tokens)
    assert logits.shape == (1, 3, cfg.vocab_size)
    assert kv.shape[0] == cfg.n_layers


def test_gemma2_logits_match():
    """Gemma-2 = GeGLU + logit softcaps + sandwich (post) norms + (1+w)
    RMSNorm + sqrt(dim) embed scaling + query_pre_attn_scalar + alternating
    local/global attention + tied embeddings.  A tiny window on a prompt
    longer than the window exercises the even-layer sliding mask."""
    hf_cfg = transformers.Gemma2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,  # even: alternation pattern fully exercised
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,  # decoupled: 4 * 32 != 64
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0,
        query_pre_attn_scalar=24.0,
        sliding_window=8,
        hidden_activation="gelu_pytorch_tanh",
        tie_word_embeddings=True,
        attention_bias=False,
    )
    torch.manual_seed(3)
    with torch.no_grad():
        model = transformers.Gemma2ForCausalLM(hf_cfg)
        for p in model.parameters():
            p.mul_(3.0)
    model.eval()
    cfg = config_from_hf(model.config, dtype=jnp.float32)
    assert cfg.act == "gelu_tanh" and cfg.post_norms and cfg.norm_offset
    assert cfg.attn_softcap == 50.0 and cfg.final_softcap == 30.0
    assert cfg.sliding_window == 8 and cfg.window_pattern == 2
    assert cfg.head_dim == 32
    params = params_from_hf(model, cfg)

    tokens = np.array(
        [[5, 17, 99, 3, 42, 200, 7, 1, 88, 23, 150, 66, 9, 4, 31, 77]],
        dtype=np.int64,
    )  # 16 tokens > window 8
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()
    got, _ = prefill_forward(params, cfg, jnp.asarray(tokens, dtype=jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got, np.float32), want, rtol=2e-3, atol=2e-3
    )


def test_mixtral_moe_logits_match_transformers():
    """MoE family import: converted Mixtral weights (per-expert w1/w3/w2
    stacks, fp32 router, Llama-convention attention) must reproduce
    transformers' logits — HF's softmax->top-k->renormalize routing equals
    our softmax-over-top-k gating exactly."""
    from infinistore_tpu.models import moe_prefill_forward
    from infinistore_tpu.models.hf import moe_config_from_hf, moe_params_from_hf

    hf_cfg = transformers.MixtralConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        sliding_window=None,
    )
    torch.manual_seed(3)
    with torch.no_grad():
        model = transformers.MixtralForCausalLM(hf_cfg)
        for p in model.parameters():
            p.mul_(3.0)
    model.eval()

    cfg = moe_config_from_hf(model.config, dtype=jnp.float32)
    assert cfg.n_experts == 4 and cfg.top_k == 2
    params = moe_params_from_hf(model, cfg)

    tokens = np.array([[5, 17, 99, 3, 42, 200, 7, 1]], dtype=np.int64)
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()
    got, _ = moe_prefill_forward(params, cfg, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got, np.float32), want, rtol=2e-3, atol=2e-3
    )
