"""Resilient store data plane: per-op deadlines, circuit-breaker degraded
serving, and the deterministic fault-injection harness.

The contract under test (docs/robustness.md): a store-tier failure — dead
server, hung server, flapping server, mid-op connection kill — degrades
serving to recompute, never to a user-visible error or an unbounded hang.
Every scenario here is driven deterministically through the python
server's ``FaultInjector`` (manage-plane ``POST /faults``), not through
sleep-and-hope races.
"""

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import infinistore_tpu as ist
from infinistore_tpu.utils import metrics as m
from infinistore_tpu.utils.resilience import (
    CircuitBreaker,
    Deadline,
    RetryPolicy,
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _boot(port, mport, extra_env=None):
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", "python"],
        env={**os.environ, "JAX_PLATFORMS": "cpu", **(extra_env or {})},
    )
    deadline = time.time() + 25
    for p in (port, mport):
        while True:
            if proc.poll() is not None:
                pytest.fail("server process failed to start")
            try:
                socket.create_connection(("127.0.0.1", p), timeout=0.5).close()
                break
            except OSError:
                if time.time() >= deadline:
                    proc.kill()
                    pytest.fail(f"server port {p} did not come up")
                time.sleep(0.1)
    return proc


def _stop(proc):
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def _arm(mport, rules):
    req = urllib.request.Request(
        f"http://127.0.0.1:{mport}/faults", method="POST",
        data=json.dumps(rules).encode(),
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.load(r)


def _healthz(mport):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mport}/healthz", timeout=10
    ) as r:
        return json.load(r)


def _store_metrics(mport):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mport}/metrics", timeout=10
    ) as r:
        return m.parse_prometheus_text(r.read().decode())


@pytest.fixture(scope="module")
def server():
    port, mport = _free_port(), _free_port()
    proc = _boot(port, mport)
    yield port, mport
    _stop(proc)


@pytest.fixture(autouse=True)
def _clear_faults(server):
    yield
    try:
        _arm(server[1], [])
    except OSError:
        pass


def _conn(port, op_timeout_s=None, **kw):
    c = ist.InfinityConnection(ist.ClientConfig(
        host_addr="127.0.0.1", service_port=port,
        connection_type=ist.TYPE_SHM, op_timeout_s=op_timeout_s,
        log_level="error", **kw,
    ))
    c.connect()
    return c


# ---- resilience primitives (no server) ----


def test_deadline_and_retry_policy_budget():
    now = [0.0]
    dl = Deadline(5.0, time_fn=lambda: now[0])
    assert not dl.expired and dl.remaining() == 5.0
    now[0] = 4.0
    assert dl.remaining(cap=10.0) == pytest.approx(1.0)
    now[0] = 5.0
    assert dl.expired and dl.remaining() == 0.0
    assert Deadline(None).remaining() is None

    # attempts bound: max_attempts=3 -> 2 sleeps between 3 tries
    p = RetryPolicy(max_attempts=3, base_delay_s=0.01, budget_s=100.0,
                    jitter=False, time_fn=lambda: 0.0)
    assert list(p.backoff()) == [0.01, 0.02]
    # budget bound: the clock advances past the budget -> generator ends
    t = [0.0]
    p = RetryPolicy(max_attempts=0, base_delay_s=0.01, budget_s=1.0,
                    jitter=False, time_fn=lambda: t[0])
    it = p.backoff()
    assert next(it) == 0.01
    t[0] = 2.0
    assert next(it, None) is None
    # full jitter stays within (0, delay]
    p = RetryPolicy(max_attempts=5, base_delay_s=0.1, jitter=True,
                    rng=lambda: 0.5, time_fn=lambda: 0.0)
    assert list(p.backoff())[:2] == [0.05, 0.1]

    # run(): retries then surfaces the last error
    calls = []

    def flaky():
        calls.append(1)
        raise ValueError("nope")

    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=3, base_delay_s=0.001).run(
            flaky, retry_on=(ValueError,), sleep=lambda _s: None
        )
    assert len(calls) == 3


def test_circuit_breaker_transitions_and_metrics():
    now = [0.0]
    reg = m.MetricsRegistry()
    cb = CircuitBreaker(name="t", failure_threshold=2, cooldown_s=10.0,
                        registry=reg, time_fn=lambda: now[0])
    assert cb.state == "closed" and cb.allow()
    cb.record_failure()
    assert cb.state == "closed"  # below threshold
    cb.record_failure()
    assert cb.state == "open" and not cb.allow()
    # a success between failures resets the consecutive count
    cb2 = CircuitBreaker(name="t2", failure_threshold=2, registry=reg)
    cb2.record_failure()
    cb2.record_success()
    cb2.record_failure()
    assert cb2.state == "closed"
    # cooldown elapses -> half-open, exactly ONE probe
    now[0] = 10.0
    assert cb.allow() and cb.state == "half-open"
    assert not cb.allow()  # second caller: probe already in flight
    # probe failure reopens with a fresh cooldown
    cb.record_failure()
    assert cb.state == "open"
    now[0] = 15.0
    assert not cb.allow()  # fresh cooldown from t=10
    now[0] = 20.0
    assert cb.allow()
    cb.record_success()
    assert cb.state == "closed" and cb.allow()
    # the transition history is scrapeable
    parsed = m.parse_prometheus_text(reg.to_prometheus_text())
    trans = {
        labels: v for (name, labels), v in parsed.items()
        if name == "istpu_store_circuit_transitions_total"
        and ("name", "t") in labels
    }
    by_to = {dict(k)["to"]: v for k, v in trans.items()}
    assert by_to == {"open": 2.0, "half-open": 2.0, "closed": 1.0}


def test_prometheus_text_parser_roundtrip():
    reg = m.MetricsRegistry()
    reg.counter("a_total", "help", labelnames=("x",)).labels("v 1").inc(3)
    reg.gauge("b").set(2.5)
    parsed = m.parse_prometheus_text(reg.to_prometheus_text())
    assert parsed[("a_total", (("x", "v 1"),))] == 3.0
    assert parsed[("b", ())] == 2.5


# ---- fault injection + client deadlines over the wire ----


def test_hung_op_fails_within_deadline_then_recovers(server):
    """The acceptance hang: a stalled GET_DESC must fail within
    op_timeout_s (never block unboundedly), kill the channel so FIFO
    matching stays sound, and recover through the normal reconnect path
    once the stall clears."""
    port, mport = server
    conn = _conn(port, op_timeout_s=1.0)
    src = np.arange(4096, dtype=np.float32)
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)
    conn.write_cache([("hang-k", 0)], 4096 * 4, src.ctypes.data)

    assert _arm(mport, [{"op": "GET_DESC", "action": "stall"}])["armed"] == 1
    assert _healthz(mport)["status"] == "degraded"

    t0 = time.perf_counter()
    with pytest.raises(ist.InfiniStoreConnectionError):
        # reconnect retries once (the stall persists), so the op costs at
        # most ~2 deadlines — bounded either way
        conn.read_cache([("hang-k", 0)], 4096 * 4, dst.ctypes.data)
    dt = time.perf_counter() - t0
    assert dt < 5.0, f"hung op took {dt:.1f}s — deadline did not bound it"

    _arm(mport, [])
    assert _healthz(mport)["status"] == "ok"
    conn.read_cache([("hang-k", 0)], 4096 * 4, dst.ctypes.data)
    np.testing.assert_array_equal(src, dst)
    conn.close()


def test_injected_error_is_absorbed_by_reconnect(server):
    """A single injected SYSTEM_ERROR is a transport failure the client's
    reconnect-and-retry absorbs transparently; the injection is visible in
    the store's fault counter."""
    port, mport = server
    conn = _conn(port, op_timeout_s=5.0)
    before = _store_metrics(mport).get(
        ("istpu_store_faults_injected_total",
         (("action", "error"), ("op", "EXIST"))), 0.0)
    _arm(mport, [{"op": "EXIST", "action": "error", "times": 1}])
    assert conn.check_exist("whatever") is False  # retried, then answered
    after = _store_metrics(mport)[
        ("istpu_store_faults_injected_total",
         (("action", "error"), ("op", "EXIST")))]
    assert after == before + 1
    conn.close()


def test_injected_delay_slows_only_matching_ops(server):
    port, mport = server
    conn = _conn(port, op_timeout_s=5.0)
    _arm(mport, [{"op": "EXIST", "action": "delay", "delay_s": 0.4}])
    t0 = time.perf_counter()
    conn.check_exist("delayed")
    assert time.perf_counter() - t0 >= 0.4
    # non-matching op is unaffected
    t0 = time.perf_counter()
    with pytest.raises(ist.InfiniStoreException):
        conn.get_match_last_index(["zz-nomatch"])
    assert time.perf_counter() - t0 < 0.3
    conn.close()


def test_drop_conn_after_skips_then_kills(server):
    """``after`` makes mid-batch kills deterministic: the first N matching
    ops pass, the N+1st dies mid-op."""
    port, mport = server
    conn = _conn(port, op_timeout_s=5.0)
    _arm(mport, [{"op": "EXIST", "action": "drop_conn", "after": 1,
                  "times": 1}])
    assert conn.check_exist("nope-1") is False  # the free pass
    # second EXIST: connection killed mid-op -> reconnect retries -> rule
    # exhausted (times=1) -> succeeds transparently
    assert conn.check_exist("nope-2") is False
    conn.close()


def test_concurrent_pipelined_ops_survive_server_restart():
    """Two threads mid pipelined write/read while the server is killed and
    restarted: every op either completes or raises a connection-class
    error — never hangs, never interleaves corrupt data.  Byte parity is
    re-verified end to end after recovery."""
    port, mport = _free_port(), _free_port()
    proc = _boot(port, mport)
    nb, blk = 16, 16 << 10
    stop = threading.Event()
    errs = []

    def worker(wid):
        conn = _conn(port, op_timeout_s=2.0, auto_reconnect=True)
        src = (np.arange(nb * blk, dtype=np.uint8) + wid).astype(np.uint8)
        dst = np.zeros_like(src)
        conn.register_mr(src)
        conn.register_mr(dst)
        it = 0
        try:
            while not stop.is_set():
                it += 1
                blocks = [(f"cw{wid}-{it}-{i}", i * blk) for i in range(nb)]
                try:
                    conn.write_cache_pipelined([(blocks, blk, src.ctypes.data)])
                    dst[:] = 0
                    conn.read_cache_pipelined(
                        [(blocks, blk, dst.ctypes.data)]
                    )
                    if not np.array_equal(src, dst):
                        errs.append((wid, "corrupt data after read"))
                        return
                except (ist.InfiniStoreException, OSError):
                    # outage window: connection-class failures are the
                    # contract; anything else (hang, corruption) is not
                    time.sleep(0.05)
        except BaseException as e:  # noqa: BLE001
            errs.append((wid, repr(e)))

    threads = [threading.Thread(target=worker, args=(w,)) for w in (1, 2)]
    for t in threads:
        t.start()
    try:
        time.sleep(1.0)          # both threads mid-traffic
        proc.kill()              # hard kill, no goodbye
        proc.wait(timeout=10)
        time.sleep(1.0)          # threads churn against the dead server
        proc = _boot(port, mport)
        time.sleep(2.0)          # threads recover and keep verifying parity
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "worker hung"
    assert not errs, errs

    # post-recovery parity through a fresh connection
    conn = _conn(port, op_timeout_s=2.0)
    src = np.random.randint(0, 256, nb * blk, dtype=np.uint8)
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)
    blocks = [(f"post-{i}", i * blk) for i in range(nb)]
    conn.write_cache_pipelined([(blocks, blk, src.ctypes.data)])
    conn.read_cache_pipelined([(blocks, blk, dst.ctypes.data)])
    np.testing.assert_array_equal(src, dst)
    conn.close()
    _stop(proc)


# ---- periodic-evict loop resilience (in-process) ----


def test_periodic_evict_survives_store_errors():
    """The evict task must survive a raising ``Store.evict`` — before this
    fix it died permanently and silently, ending in a full pool."""
    import asyncio

    from infinistore_tpu.config import ServerConfig
    from infinistore_tpu.pyserver import StoreServer

    config = ServerConfig(
        service_port=_free_port(), manage_port=_free_port(),
        prealloc_size=1, minimal_allocate_size=64, backend="python",
        evict_interval=0.01,
    )
    srv = StoreServer(config)
    calls = []

    def boom(mn, mx):
        calls.append(1)
        if len(calls) <= 2:
            raise RuntimeError("evict blew up")
        return 0

    srv.store.evict = boom

    async def run():
        srv.start_periodic_evict()
        while len(calls) < 4:  # survived the 2 failures and kept running
            await asyncio.sleep(0.01)
        assert not srv._evict_task.done()
        srv._evict_task.cancel()

    try:
        asyncio.run(asyncio.wait_for(run(), timeout=10))
    finally:
        srv.store.evict = lambda mn, mx: 0
        srv.store.close()
    assert srv._c_evict_err.value == 2
    assert srv.degraded()  # evict errors flip the store health signal


# ---- engine + serving degradation (the chaos acceptance test) ----


jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from infinistore_tpu.engine import InferenceEngine, StoreConnector  # noqa: E402
from infinistore_tpu.kv import PagedCacheConfig  # noqa: E402
from infinistore_tpu.models import TINY, init_params, scaled  # noqa: E402
from infinistore_tpu.serve import ServingServer  # noqa: E402

from conftest import make_dense_greedy  # noqa: E402

CFG = scaled(TINY, dtype=jnp.float32)
PARAMS = init_params(CFG, jax.random.PRNGKey(7))
T = 4
PROMPT = [11, 42, 7, 99, 5, 3, 17, 28, 64, 1, 2]

dense_greedy = make_dense_greedy(PARAMS, CFG)


def make_pc(n_blocks=64):
    return PagedCacheConfig(
        n_layers=CFG.n_layers, n_kv_heads=CFG.n_kv_heads,
        head_dim=CFG.head_dim, n_blocks=n_blocks, block_tokens=T,
        dtype=CFG.dtype,
    )


def test_streamer_counts_drops_and_reports_them_at_flush(server):
    """Satellite: a parked push error must not silently eat the queued
    pushes behind it — they are counted, and the flush-time re-raise
    names the blast radius."""
    port, _ = server
    conn = _conn(port, op_timeout_s=5.0)
    eng = InferenceEngine(
        PARAMS, CFG, make_pc(), conn=conn, model_id="drop-count",
        prefill_chunk=T, store_durability="relaxed",
    )

    def boom(token):
        raise RuntimeError("push failed hard")

    # the streamer's worker half is push_commit (push_begin runs on the
    # submitting thread and must stay cheap/unfailing)
    eng.transfer.push_commit = boom
    before = m.parse_prometheus_text(
        m.default_registry().to_prometheus_text()
    ).get(("istpu_store_push_dropped_total", (("reason", "push_error"),)), 0.0)
    st = eng.prefill(PROMPT)  # 2 complete chunks -> 2 failed pushes
    with pytest.raises(RuntimeError, match=r"push failed hard.*2 queued"):
        eng.store_flush()
    eng.store_flush()  # parked state cleared; barrier reusable
    after = m.parse_prometheus_text(
        m.default_registry().to_prometheus_text()
    )[("istpu_store_push_dropped_total", (("reason", "push_error"),))]
    assert after >= before + 1
    eng.release(st)
    conn.close()


def _prompt(i):
    """Distinct 11-token prompts (same length -> same compiled shapes; the
    first token varies, so chunk keys never collide across prompts —
    repeated prompts would hit the engine's LOCAL prefix cache and make
    no store hop at all).  Keep i < 450: TINY's vocab is 512."""
    assert i < 450, i
    return [50 + i] + PROMPT[1:]


def test_engine_degrades_to_recompute_and_circuit_opens(server):
    """Store dying mid-load: lookup says hit, the load's connection is
    killed mid-op — prefill must fall back to recompute (correct greedy
    tokens).  Then a full outage (every op answered with SYSTEM_ERROR)
    opens the circuit, after which prefills skip the store outright."""
    port, mport = server
    # producer: make one prefix store-resident
    prod = _conn(port, op_timeout_s=5.0)
    a = InferenceEngine(PARAMS, CFG, make_pc(), conn=prod,
                        model_id="chaos-eng")
    a.release(a.prefill(_prompt(0)))
    a.store_flush()

    cons = _conn(port, op_timeout_s=1.0)
    b = InferenceEngine(PARAMS, CFG, make_pc(), conn=cons,
                        model_id="chaos-eng", store_durability="relaxed")
    b.breaker.failure_threshold = 2
    b.breaker.cooldown_s = 30.0
    # warmup: compile the prefill/decode shapes against a healthy store so
    # the open-circuit timing assertion below measures hops, not XLA
    st = b.prefill(_prompt(1))
    assert b.decode(st, 8) == dense_greedy(_prompt(1), 8)
    b.release(st)
    b.store_flush()

    # kill every GET_DESC mid-op: lookup (MATCH/EXIST) still answers, the
    # LOAD dies — the deterministic "store killed mid-load" failure
    _arm(mport, [{"op": "GET_DESC", "action": "drop_conn"}])
    st = b.prefill(_prompt(0))  # store-resident prefix from the producer
    assert st.reused_chunks == 0  # hit withdrawn -> full recompute
    assert b.decode(st, 8) == dense_greedy(_prompt(0), 8)
    b.release(st)
    assert b.breaker.state == "closed"  # one load failure < threshold

    # full outage: every op (HELLO included, so reconnects fail too)
    # answers SYSTEM_ERROR — fast deterministic transport failures
    _arm(mport, [{"op": "*", "action": "error"}])
    for i in (2, 3):
        st = b.prefill(_prompt(i))
        assert st.reused_chunks == 0
        assert b.decode(st, 8) == dense_greedy(_prompt(i), 8)
        b.release(st)
    deadline = time.time() + 5  # relaxed pushes fail asynchronously
    while b.breaker.state != "open" and time.time() < deadline:
        time.sleep(0.02)
    assert b.breaker.state == "open"

    # circuit open: the store is skipped outright — no timeout tax
    t0 = time.perf_counter()
    st = b.prefill(_prompt(4))
    skip_dt = time.perf_counter() - t0
    assert st.reused_chunks == 0
    assert skip_dt < 0.9, f"open circuit still paid a store hop ({skip_dt:.2f}s)"
    b.release(st)
    _arm(mport, [])
    prod.close()
    cons.close()


def test_connector_degrades_instead_of_raising(server):
    """The LMCache-style connector surface: lookup/retrieve report miss
    and store_kv reports 0 bytes when the store hop dies."""
    from infinistore_tpu.kv.cache import init_cache

    port, mport = server
    conn = _conn(port, op_timeout_s=1.0)
    sc = StoreConnector(conn, make_pc(), model_id="conn-degrade")
    sc.breaker.failure_threshold = 1
    cache = init_cache(make_pc())
    _arm(mport, [{"op": "MATCH_LAST_IDX", "action": "drop_conn"}])
    assert sc.lookup(PROMPT) == 0
    assert sc.breaker.state == "open"
    _cache2, got = sc.retrieve_kv(PROMPT, cache, [0, 1])
    assert got == 0  # circuit open: skipped, not raised
    # store_kv under an open circuit is a counted drop, not an exception
    assert sc.store_kv(PROMPT[:T], cache, [0]) == 0
    _arm(mport, [])
    conn.close()


@pytest.fixture(scope="module")
def chaos_stack():
    """A serving server attached to a dedicated store subprocess, tuned
    for fast breaker transitions."""
    port, mport = _free_port(), _free_port()
    proc = _boot(port, mport)
    conn = _conn(port, op_timeout_s=1.0)
    eng = InferenceEngine(
        PARAMS, CFG, make_pc(n_blocks=128), conn=conn,
        model_id="chaos-serve", store_durability="relaxed",
    )
    eng.decode_chunk = 4
    eng.breaker.failure_threshold = 2
    eng.breaker.cooldown_s = 0.5
    srv = ServingServer(eng, port=0, max_batch=4, model_id="chaos-serve")
    srv.start()
    yield srv, proc, port, mport
    srv.close()
    conn.close()
    _stop(proc)


def _post(port, body, timeout=180, path="/v1/completions"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data)


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_chaos_serving_completes_every_request(chaos_stack):
    """THE acceptance chaos test: with the store killed mid-load and then
    stalled, a multi-request workload completes EVERY request via
    recompute with zero error deliveries; the circuit walks open ->
    half-open -> closed across recovery, observable in /metrics; and
    /healthz flips degraded <-> ok.

    Every request uses a DISTINCT prompt (same length, first token
    varies): a repeated prompt would be served by the engine's local
    prefix cache with no store hop at all."""
    srv, proc, port, mport = chaos_stack
    n = [100]

    def ask(prompt=None):
        p = prompt if prompt is not None else _prompt(n[0])
        if prompt is None:
            n[0] += 1
        status, body = _post(srv.port, {
            "prompt": p, "max_tokens": 6, "temperature": 0,
        })
        assert status == 200, body
        assert body["choices"][0]["token_ids"] == dense_greedy(p, 6), body
        return body

    # phase 0: healthy — requests complete, pages land in the store; a
    # producer engine seeds a prefix the SERVING engine has never seen
    # locally (the mid-load-kill victim below)
    ask()
    prod_conn = _conn(port, op_timeout_s=5.0)
    prod = InferenceEngine(PARAMS, CFG, make_pc(), conn=prod_conn,
                           model_id="chaos-serve")
    victim = _prompt(200)
    prod.release(prod.prefill(victim))
    prod.store_flush()
    st, data = _get(srv.port, "/healthz")
    assert st == 200 and json.loads(data)["status"] == "ok"

    # phase 1a: the store dies MID-LOAD — lookup still answers, every
    # GET_DESC connection is killed, so the store-resident prefix is
    # found and then its load dies mid-op.  The request must complete
    # via recompute.
    _arm(mport, [{"op": "GET_DESC", "action": "drop_conn", "times": 8}])
    ask(victim)
    parsed = _store_metrics(mport)
    assert parsed.get(("istpu_store_faults_injected_total",
                       (("action", "drop_conn"), ("op", "GET_DESC"))), 0) >= 1

    # phase 1b: then the store HANGS (stall on everything — HELLO too, so
    # reconnect probes hang as well): requests keep completing, failures
    # accumulate, the circuit opens
    _arm(mport, [{"op": "*", "action": "stall"}])
    for _ in range(3):  # multi-request workload through the outage
        ask()  # every request completes via recompute — zero errors
    deadline = time.time() + 10  # relaxed pushes fail asynchronously
    while srv.engine.breaker.state != "open" and time.time() < deadline:
        time.sleep(0.05)
    assert srv.engine.breaker.state == "open"
    st, data = _get(srv.port, "/healthz")
    health = json.loads(data)
    assert health["status"] == "degraded" and health["store_circuit"] == "open"

    # while open: store hops are skipped outright — no per-request
    # timeout tax (each hop would otherwise pay >= op_timeout_s)
    t0 = time.perf_counter()
    ask()
    assert time.perf_counter() - t0 < 0.9

    # phase 2: recovery — faults cleared, cooldown elapses, the next
    # request's lookup is the half-open probe and closes the circuit
    _arm(mport, [])
    time.sleep(srv.engine.breaker.cooldown_s + 0.1)
    deadline = time.time() + 30
    while srv.engine.breaker.state != "closed" and time.time() < deadline:
        ask()
        time.sleep(0.05)
    assert srv.engine.breaker.state == "closed"
    deadline = time.time() + 10  # a clean idle flush clears the flag
    while time.time() < deadline:
        st, data = _get(srv.port, "/healthz")
        if json.loads(data)["status"] == "ok":
            break
        time.sleep(0.1)
    assert json.loads(data)["status"] == "ok", data

    # the full walk is in the serving /metrics exposition
    st, data = _get(srv.port, "/metrics")
    parsed = m.parse_prometheus_text(data.decode())
    trans = {
        dict(labels).get("to"): v for (name, labels), v in parsed.items()
        if name == "istpu_store_circuit_transitions_total"
        and dict(labels).get("name") == "store"
    }
    assert trans.get("open", 0) >= 1, trans
    assert trans.get("half-open", 0) >= 1, trans
    assert trans.get("closed", 0) >= 1, trans
    degraded = sum(
        v for (name, labels), v in parsed.items()
        if name == "istpu_store_degraded_ops_total"
    )
    assert degraded >= 1
    # circuit state gauge is exported and currently closed
    assert parsed.get(
        ("istpu_store_circuit_state", (("name", "store"),))) == 0.0
    prod_conn.close()


def test_serve_healthz_without_store():
    """A storeless server is simply ok — no circuit field, no degraded."""
    eng = InferenceEngine(PARAMS, CFG, make_pc())
    eng.decode_chunk = 4
    srv = ServingServer(eng, port=0, max_batch=2, model_id="no-store")
    srv.start()
    try:
        st, data = _get(srv.port, "/healthz")
        body = json.loads(data)
        assert st == 200 and body["status"] == "ok"
        # no store -> no circuit field, nothing degraded (the health
        # plane's alerts block rides along with zero firing)
        assert "store_circuit" not in body and "reason" not in body
        assert body.get("alerts", {}).get("firing", 0) == 0
    finally:
        srv.close()
