"""End-to-end tests against a live server subprocess.

Mirrors the reference integration suite (infinistore/test_infinistore.py):
a module-scoped server fixture, then every scenario drives the public client
API.  Buffers are numpy arrays standing in for host staging buffers (the JAX
HBM paths are covered in test_kv.py).
"""

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time
from multiprocessing import Process

import numpy as np
import pytest

import infinistore_tpu as ist


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


SERVICE_PORT = 0  # set by the server fixture for the active backend
MANAGE_PORT = 0


# The whole module runs twice: once against the asyncio server and once
# against the C++ epoll server (the reference always tests the real native
# server, infinistore/test_infinistore.py:99-571).
def _await_ports(proc, ports, deadline_s=25):
    """Block until the server process listens on EVERY port (data plane
    and manage plane bind at different moments); each port gets at least
    one probe even if earlier ports consumed the shared deadline."""
    deadline = time.time() + deadline_s
    for port in ports:
        while True:
            if proc.poll() is not None:
                pytest.fail("server process failed to start")
            try:
                socket.create_connection(("127.0.0.1", port), timeout=0.5).close()
                break
            except OSError:
                if time.time() >= deadline:
                    pytest.fail(f"server port {port} did not come up")
                time.sleep(0.1)


@pytest.fixture(scope="module", params=["python", "native"])
def server(request):
    global SERVICE_PORT, MANAGE_PORT
    SERVICE_PORT = _free_port()
    MANAGE_PORT = _free_port()
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "infinistore_tpu.server",
            "--service-port",
            str(SERVICE_PORT),
            "--manage-port",
            str(MANAGE_PORT),
            "--prealloc-size",
            "1",
            "--minimal-allocate-size",
            "16",
            "--log-level",
            "warning",
            "--backend",
            request.param,
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    # the data plane and the manage plane come up at different moments;
    # tests hit both, so probe both before yielding
    _await_ports(proc, (SERVICE_PORT, MANAGE_PORT), deadline_s=25)
    yield proc
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def make_conn(connection_type=ist.TYPE_SHM):
    config = ist.ClientConfig(
        host_addr="127.0.0.1",
        service_port=SERVICE_PORT,
        connection_type=connection_type,
    )
    conn = ist.InfinityConnection(config)
    conn.connect()
    return conn


def rand_key(n=10):
    import random
    import string

    return "".join(random.choice(string.ascii_letters + string.digits) for _ in range(n))


@pytest.mark.parametrize("dtype", [np.float16, np.float32])
def test_basic_read_write_cache(server, dtype):
    """Reference parity: test_basic_read_write_cache."""
    conn = make_conn()
    key = rand_key()
    src = np.arange(4096, dtype=dtype)
    conn.register_mr(src)
    esize = src.itemsize

    asyncio.run(conn.write_cache_async([(key, 0)], 4096 * esize, src.ctypes.data))
    conn.close()

    conn = make_conn()
    dst = np.zeros(4096, dtype=dtype)
    conn.register_mr(dst)
    asyncio.run(conn.read_cache_async([(key, 0)], 4096 * esize, dst.ctypes.data))
    np.testing.assert_array_equal(src, dst)
    conn.close()


@pytest.mark.parametrize("connection_type", [ist.TYPE_SHM, ist.TYPE_TCP])
def test_batch_read_write_cache(server, connection_type):
    """Reference parity: test_batch_read_write_cache (both transports)."""
    conn = make_conn(connection_type)
    num_blocks, block_elems = 10, 4096
    src = np.arange(num_blocks * block_elems, dtype=np.float32)
    conn.register_mr(src)

    async def run():
        for _ in range(3):
            keys = [rand_key() for _ in range(num_blocks)]
            blocks = [(keys[i], i * block_elems * 4) for i in range(num_blocks)]
            await conn.write_cache_async(blocks, block_elems * 4, src.ctypes.data)
            dst = np.zeros(num_blocks * block_elems, dtype=np.float32)
            conn.register_mr(dst)
            await conn.read_cache_async(blocks, block_elems * 4, dst.ctypes.data)
            np.testing.assert_array_equal(src, dst)

    asyncio.run(run())
    conn.close()


def _client_roundtrip(port):
    config = ist.ClientConfig(
        host_addr="127.0.0.1", service_port=port, connection_type=ist.TYPE_SHM
    )
    conn = ist.InfinityConnection(config)
    conn.connect()
    key = rand_key()
    src = np.arange(4096, dtype=np.float32)
    conn.register_mr(src)
    asyncio.run(conn.write_cache_async([(key, 0)], 4096 * 4, src.ctypes.data))
    conn.close()

    conn = ist.InfinityConnection(config)
    conn.connect()
    dst = np.zeros(4096, dtype=np.float32)
    conn.register_mr(dst)
    asyncio.run(conn.read_cache_async([(key, 0)], 4096 * 4, dst.ctypes.data))
    np.testing.assert_array_equal(src, dst)
    conn.close()


def test_multiple_clients(server):
    """Reference parity: test_multiple_clients."""
    procs = [Process(target=_client_roundtrip, args=(SERVICE_PORT,)) for _ in range(2)]
    for p in procs:
        p.start()
    for p in procs:
        p.join()
        assert p.exitcode == 0


def test_key_check(server):
    conn = make_conn()
    key = rand_key(5)
    src = np.random.randn(4096).astype(np.float32)
    conn.register_mr(src)
    asyncio.run(conn.write_cache_async([(key, 0)], 4096 * 4, src.ctypes.data))
    assert conn.check_exist(key)
    assert not conn.check_exist("definitely_missing")
    conn.close()


def test_get_match_last_index(server):
    """Reference parity: test_get_match_last_index."""
    conn = make_conn()
    src = np.random.randn(4096).astype(np.float32)
    conn.register_mr(src)
    asyncio.run(
        conn.write_cache_async(
            [("key1", 0), ("key2", 1024), ("key3", 2048)], 1024 * 4, src.ctypes.data
        )
    )
    assert conn.get_match_last_index(["A", "B", "C", "key1", "D", "E"]) == 3
    conn.close()


def test_get_match_no_match_raises(server):
    conn = make_conn()
    with pytest.raises(ist.InfiniStoreException):
        conn.get_match_last_index(["zzz_no", "zzz_way"])
    conn.close()


def test_key_not_found(server):
    """Reference parity: test_key_not_found / test_read_non_exist_key."""
    conn = make_conn()
    dst = np.zeros(4096, dtype=np.float32)
    conn.register_mr(dst)
    with pytest.raises(ist.InfiniStoreKeyNotFound):
        asyncio.run(
            conn.read_cache_async([("non_exist_key", 0)], 4096 * 4, dst.ctypes.data)
        )
    conn.close()


def test_upload_one_conn_download_another(server):
    """Reference parity: test_upload_cpu_download_gpu."""
    src_conn = make_conn()
    dst_conn = make_conn()
    key = rand_key(5)
    src = np.random.randn(4096).astype(np.float32)
    dst = np.zeros(4096, dtype=np.float32)
    src_conn.register_mr(src)
    dst_conn.register_mr(dst)

    async def run():
        await src_conn.write_cache_async([(key, 0)], 4096 * 4, src.ctypes.data)
        await dst_conn.read_cache_async([(key, 0)], 4096 * 4, dst.ctypes.data)

    asyncio.run(run())
    np.testing.assert_array_equal(src, dst)
    src_conn.close()
    dst_conn.close()


def test_async_api(server):
    """Reference parity: test_async_api (connect_async + awaited ops)."""
    config = ist.ClientConfig(
        host_addr="127.0.0.1",
        service_port=SERVICE_PORT,
        connection_type=ist.TYPE_SHM,
    )
    conn = ist.InfinityConnection(config)

    async def run():
        await conn.connect_async()
        key = rand_key(5)
        src = np.random.randn(4096).astype(np.float32)
        dst = np.zeros(4096, dtype=np.float32)
        conn.register_mr(src)
        conn.register_mr(dst)
        await conn.write_cache_async([(key, 0)], 4096 * 4, src.ctypes.data)
        await conn.read_cache_async([(key, 0)], 4096 * 4, dst.ctypes.data)
        np.testing.assert_array_equal(src, dst)
        conn.close()

    asyncio.run(run())


def test_delete_keys(server):
    """Reference parity: test_delete_keys."""
    conn = make_conn()
    src = np.random.randn(4096).astype(np.float32)
    keys = [rand_key() for _ in range(3)]
    conn.register_mr(src)
    asyncio.run(
        conn.write_cache_async(
            [(keys[i], i * 1024 * 4) for i in range(3)], 1024 * 4, src.ctypes.data
        )
    )
    for k in keys:
        assert conn.check_exist(k)
    assert conn.delete_keys([keys[0], keys[2]]) == 2
    assert conn.check_exist(keys[1])
    assert not conn.check_exist(keys[0])
    assert not conn.check_exist(keys[2])
    conn.close()


def test_simple_tcp_read_write(server):
    """Reference parity: test_simple_tcp_read_write."""
    conn = make_conn(ist.TYPE_TCP)
    key = rand_key()
    size = 256 * 1024
    src = np.arange(size, dtype=np.uint8) % 200
    conn.tcp_write_cache(key, src.ctypes.data, size)
    dst = conn.tcp_read_cache(key)
    np.testing.assert_array_equal(np.asarray(dst), src)
    # client-side observability: the data-path ops were timed
    stats = conn.latency_stats()
    if stats:  # python client only; native keeps timings in the C runtime
        assert stats["w_tcp"]["count"] == 1
        assert stats["r_tcp"]["count"] == 1
        assert stats["w_tcp"]["avg_ms"] > 0
    conn.close()


def test_overwrite_tcp(server):
    """Reference parity: test_overwrite_tcp."""
    conn = make_conn(ist.TYPE_TCP)
    key = rand_key()
    size = 256 * 1024
    src = np.arange(size, dtype=np.uint8) % 200
    conn.tcp_write_cache(key, src.ctypes.data, size)
    src2 = np.arange(size, dtype=np.uint8) % 100
    conn.tcp_write_cache(key, src2.ctypes.data, size)
    dst = conn.tcp_read_cache(key)
    np.testing.assert_array_equal(np.asarray(dst), src2)
    conn.close()


def test_manage_plane(server, request):
    import json
    import urllib.request

    backend = request.node.callspec.params["server"]

    with urllib.request.urlopen(
        f"http://127.0.0.1:{MANAGE_PORT}/selftest", timeout=30
    ) as r:
        assert json.load(r)["status"] == "ok"
    with urllib.request.urlopen(
        f"http://127.0.0.1:{MANAGE_PORT}/kvmap_len", timeout=30
    ) as r:
        assert json.load(r)["len"] >= 0
    with urllib.request.urlopen(
        f"http://127.0.0.1:{MANAGE_PORT}/healthz", timeout=30
    ) as r:
        assert json.load(r)["status"] == "ok"
    with urllib.request.urlopen(
        f"http://127.0.0.1:{MANAGE_PORT}/stats", timeout=30
    ) as r:
        m = json.load(r)
    assert "usage" in m and "puts" in m
    if backend == "python":
        # allocator-shape observability (fragmentation, leases) lives in
        # the python store core; the C runtime keeps the reference schema
        assert "fragmentation" in m and "active_read_leases" in m
    # server-side per-op latency accumulators (both backends): earlier
    # tests in this module already drove puts/gets through this server
    lat = m.get("op_latency", {})
    assert lat, m
    assert any(
        v.get("count", 0) > 0 and v.get("avg_ms", -1) >= 0
        for v in lat.values()
    ), lat
    # Prometheus exposition (/metrics.prom is the back-compat alias)
    for path in ("/metrics", "/metrics.prom"):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{MANAGE_PORT}{path}", timeout=30
        ) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert "# TYPE infinistore_tpu_usage gauge" in text
        assert "infinistore_tpu_puts" in text


def test_purge_via_manage_plane(server):
    import json
    import urllib.request

    conn = make_conn()
    src = np.ones(1024, dtype=np.float32)
    conn.register_mr(src)
    key = rand_key()
    asyncio.run(conn.write_cache_async([(key, 0)], 1024 * 4, src.ctypes.data))
    assert conn.check_exist(key)
    req = urllib.request.Request(
        f"http://127.0.0.1:{MANAGE_PORT}/purge", method="POST"
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        assert json.load(r)["status"] == "ok"
    assert not conn.check_exist(key)
    conn.close()


def test_concurrent_async_writers_one_connection(server):
    """Many in-flight async ops on one connection must not corrupt frames."""
    conn = make_conn()
    src = np.arange(64 * 1024, dtype=np.float32)
    conn.register_mr(src)

    async def run():
        tasks = []
        for j in range(16):
            blocks = [(f"cc{j}_{i}", i * 4096) for i in range(8)]
            tasks.append(conn.write_cache_async(blocks, 4096, src.ctypes.data))
        await asyncio.gather(*tasks)
        dst = np.zeros_like(src)
        conn.register_mr(dst)
        reads = []
        for j in range(16):
            blocks = [(f"cc{j}_{i}", i * 4096) for i in range(8)]
            reads.append(conn.read_cache_async(blocks, 4096, dst.ctypes.data))
        await asyncio.gather(*reads)
        np.testing.assert_array_equal(dst[: 8 * 1024], src[: 8 * 1024])

    asyncio.run(run())
    conn.close()


@pytest.mark.parametrize("client_mode", ["python", "native"])
def test_client_matrix_roundtrip(server, client_mode, monkeypatch):
    """Both client implementations against both server backends."""
    if client_mode == "native":
        from infinistore_tpu import _native

        if not _native.available():
            pytest.skip("native client library not built")
    monkeypatch.setenv("ISTPU_CLIENT", client_mode)
    conn = make_conn()
    key = rand_key()
    src = np.random.randn(4096).astype(np.float32)
    dst = np.zeros(4096, dtype=np.float32)
    conn.register_mr(src)
    conn.register_mr(dst)
    asyncio.run(conn.write_cache_async([(key, 0)], 4096 * 4, src.ctypes.data))
    asyncio.run(conn.read_cache_async([(key, 0)], 4096 * 4, dst.ctypes.data))
    np.testing.assert_array_equal(src, dst)
    conn.close()


def test_bf16_roundtrip(server):
    """bf16 is the serving dtype; raw bytes must round-trip unscathed."""
    import ml_dtypes

    conn = make_conn()
    key = rand_key()
    src = np.arange(4096).astype(ml_dtypes.bfloat16)
    dst = np.zeros(4096, dtype=ml_dtypes.bfloat16)
    conn.register_mr(src)
    conn.register_mr(dst)
    asyncio.run(conn.write_cache_async([(key, 0)], 4096 * 2, src.ctypes.data))
    asyncio.run(conn.read_cache_async([(key, 0)], 4096 * 2, dst.ctypes.data))
    np.testing.assert_array_equal(src.view(np.uint16), dst.view(np.uint16))
    conn.close()


def _alive_probe():
    conn = make_conn()
    key = rand_key()
    src = np.ones(1024, dtype=np.float32)
    conn.register_mr(src)
    asyncio.run(conn.write_cache_async([(key, 0)], 1024 * 4, src.ctypes.data))
    assert conn.check_exist(key)
    conn.close()


def test_malformed_frames_drop_connection_not_server(server):
    """Garbage header and adversarial key counts cost the sender its
    connection; the server must keep serving other clients."""
    from infinistore_tpu import protocol as P

    # 1. garbage bytes where a header belongs
    s = socket.create_connection(("127.0.0.1", SERVICE_PORT), timeout=5)
    s.sendall(b"\xde\xad\xbe\xef" * 16)
    s.settimeout(5)
    try:
        assert s.recv(1) == b""  # orderly close...
    except ConnectionResetError:
        pass  # ...or RST; both mean the server dropped us
    s.close()

    # 2. valid header, adversarial key count (2^32-1 keys in a 4-byte body)
    s = socket.create_connection(("127.0.0.1", SERVICE_PORT), timeout=5)
    bomb = (0xFFFFFFFF).to_bytes(4, "little")
    s.sendall(P.pack_header(P.OP_DELETE_KEYS, len(bomb)) + bomb)
    s.settimeout(5)
    try:
        got = s.recv(P.RESP_SIZE)
        # either an INVALID_REQ response or a drop is acceptable; a crash is not
        if got:
            status, _ = P.RESP.unpack(got)
            assert status == P.INVALID_REQ
    except ConnectionResetError:
        pass
    s.close()

    _alive_probe()


def test_protocol_fuzz_random_bodies(server):
    """Valid headers with random/truncated bodies across every op id: any
    response or drop is fine, a server crash is not (the reference's
    bad-frame handling; guards the untrusted-count paths in
    src/protocol.h::Reader)."""
    import random

    from infinistore_tpu import protocol as P

    rng = random.Random(0xC0FFEE)
    for op in list(range(0, 20)):
        for body_len in (0, 1, 4, 37, 256):
            body = bytes(rng.randrange(256) for _ in range(body_len))
            s = socket.create_connection(("127.0.0.1", SERVICE_PORT), timeout=5)
            s.settimeout(5)
            try:
                s.sendall(P.pack_header(op, len(body)) + body)
                s.recv(P.RESP_SIZE)  # response, close, or reset: all fine
            except OSError:
                pass
            finally:
                s.close()
    # header claims a bigger body than it sends, then disconnects
    s = socket.create_connection(("127.0.0.1", SERVICE_PORT), timeout=5)
    s.sendall(P.pack_header(P.OP_PUT_INLINE, 1 << 20) + b"short")
    s.close()

    _alive_probe()


def test_client_death_mid_stream_reclaims_pending(server):
    """A client killed midway through a PUT_INLINE_BATCH payload must not
    leak pending regions (reference aborts uncommitted keys on disconnect)."""
    import json
    import urllib.request

    from infinistore_tpu import protocol as P

    block = 64 << 10
    keys = [f"dead_{rand_key()}".encode() for _ in range(4)]
    body = P.pack_put_inline_batch(keys, block)
    s = socket.create_connection(("127.0.0.1", SERVICE_PORT), timeout=5)
    s.sendall(P.pack_header(P.OP_PUT_INLINE_BATCH, len(body)) + body)
    s.sendall(b"x" * (block + 100))  # a fraction of the 4-block payload
    s.close()  # die mid-stream

    deadline = time.time() + 10
    while time.time() < deadline:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{MANAGE_PORT}/stats", timeout=5
        ) as r:
            if json.load(r).get("pending", 1) == 0:
                break
        time.sleep(0.2)
    else:
        pytest.fail("pending regions were not reclaimed after client death")
    conn = make_conn()
    for k in keys:  # uncommitted keys must never have become visible
        assert not conn.check_exist(k.decode())
    conn.close()


def _client_stress(port, worker_id):
    config = ist.ClientConfig(
        host_addr="127.0.0.1", service_port=port, connection_type=ist.TYPE_SHM
    )
    conn = ist.InfinityConnection(config)
    conn.connect()
    n_blocks, elems = 8, 1024
    src = (np.arange(n_blocks * elems, dtype=np.float32) + worker_id).copy()
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)
    for it in range(10):
        blocks = [(f"st{worker_id}_{it}_{i}", i * elems * 4) for i in range(n_blocks)]
        asyncio.run(conn.write_cache_async(blocks, elems * 4, src.ctypes.data))
        asyncio.run(conn.read_cache_async(blocks, elems * 4, dst.ctypes.data))
        np.testing.assert_array_equal(src, dst)
    conn.close()


def test_multiprocess_stress(server):
    """4 concurrent writer/reader processes on one server (reference:
    test_infinistore.py multi-client scenarios)."""
    procs = [
        Process(target=_client_stress, args=(SERVICE_PORT, w)) for w in range(4)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=60)
        assert p.exitcode == 0


def test_slow_reader_survives_delete_and_reuse(server):
    """Zero-copy GET segments queued behind a slow receiver must survive a
    concurrent delete + block reuse (the server pins the regions)."""
    from infinistore_tpu import protocol as P

    n_keys, block = 512, 64 << 10  # 32 MB: far beyond kernel socket buffers
    payload = np.random.randint(0, 256, n_keys * block, dtype=np.uint8)
    conn = make_conn()
    conn.register_mr(payload)
    keys = [f"slow_{rand_key()}" for _ in range(n_keys)]
    asyncio.run(
        conn.write_cache_async(
            [(keys[i], i * block) for i in range(n_keys)], block, payload.ctypes.data
        )
    )

    # request everything over TCP inline-batch but do NOT read the response
    # (modest receive buffer, set before connect so it bounds the window)
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 256 << 10)
    s.settimeout(10)
    s.connect(("127.0.0.1", SERVICE_PORT))
    body = P.pack_get_inline_batch([k.encode() for k in keys], block)
    s.sendall(P.pack_header(P.OP_GET_INLINE_BATCH, len(body)) + body)
    time.sleep(0.5)  # let the server queue the zero-copy segments

    # delete the keys and force the freed blocks to be reused
    asyncio.run(conn.delete_keys_async(keys)) if hasattr(
        conn, "delete_keys_async"
    ) else conn.delete_keys(keys)
    refill = np.zeros(block, dtype=np.uint8)
    conn.register_mr(refill)
    for j in range(min(n_keys, 32)):
        asyncio.run(
            conn.write_cache_async([(f"refill_{j}", 0)], block, refill.ctypes.data)
        )

    # now drain the response slowly and verify byte integrity
    def read_exact(sock, n):
        out = bytearray()
        while len(out) < n:
            chunk = sock.recv(min(1 << 16, n - len(out)))
            if not chunk:
                raise AssertionError("connection died mid-response")
            out.extend(chunk)
        return bytes(out)

    s.settimeout(30)
    status, body_len = P.RESP.unpack(read_exact(s, P.RESP_SIZE))
    assert status == P.FINISH
    sizes = read_exact(s, 4 * n_keys)
    got = read_exact(s, body_len - 4 * n_keys)
    assert got == payload.tobytes()
    s.close()
    conn.close()


def test_pipelined_big_gets_preserve_wire_order(server, monkeypatch):
    """Several large GET_INLINE_BATCH responses queued on ONE socket must
    come back in order with intact payloads (regression: the native server
    once interleaved response headers with zero-copy payload segments)."""
    monkeypatch.setenv("ISTPU_CLIENT", "python")
    config = ist.ClientConfig(
        host_addr="127.0.0.1",
        service_port=SERVICE_PORT,
        connection_type=ist.TYPE_TCP,
        num_streams=1,  # force every op onto one pipelined channel
    )
    conn = ist.InfinityConnection(config)
    conn.connect()
    nb, blk = 16, 256 << 10  # 4 MB per batch
    srcs = []
    for j in range(6):
        src = np.random.randint(0, 256, nb * blk, dtype=np.uint8)
        srcs.append(src)
        conn.register_mr(src)
        blocks = [(f"po{j}_{i}", i * blk) for i in range(nb)]
        asyncio.run(conn.write_cache_async(blocks, blk, src.ctypes.data))

    dsts = [np.zeros(nb * blk, dtype=np.uint8) for _ in range(6)]

    async def flood_reads():
        tasks = []
        for j in range(6):
            blocks = [(f"po{j}_{i}", i * blk) for i in range(nb)]
            tasks.append(
                conn.read_cache_async(blocks, blk, dsts[j].ctypes.data)
            )
        await asyncio.gather(*tasks)

    asyncio.run(flood_reads())
    for j in range(6):
        np.testing.assert_array_equal(srcs[j], dsts[j])
    conn.close()


class _LatencyProxy:
    """TCP proxy adding a constant one-way delay upstream while preserving
    pipelining: each received chunk is forwarded at receive_time + delay, so
    back-to-back requests still overlap in flight (pure latency, not a
    throughput cap)."""

    def __init__(self, upstream_port: int, delay_s: float):
        import threading

        self.upstream_port = upstream_port
        self.delay = delay_s
        self.listener = socket.socket()
        self.listener.bind(("127.0.0.1", 0))
        self.listener.listen(8)
        self.port = self.listener.getsockname()[1]
        self.alive = True
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        import threading

        while self.alive:
            try:
                cli, _ = self.listener.accept()
            except OSError:
                return
            up = socket.create_connection(("127.0.0.1", self.upstream_port))
            for src, dst, delayed in ((cli, up, True), (up, cli, False)):
                threading.Thread(
                    target=self._pump, args=(src, dst, delayed), daemon=True
                ).start()

    def _pump(self, src, dst, delayed):
        if not delayed:
            self._relay(src, dst)
            return
        # receive and forward in separate threads so chunk i+1 can be read
        # while chunk i is still waiting out its delay — constant added
        # latency, not a one-chunk-per-delay throughput cap
        import queue
        import threading

        q: "queue.Queue" = queue.Queue()

        def sender():
            while True:
                item = q.get()
                if item is None:
                    break
                due, data = item
                rem = due - time.perf_counter()
                if rem > 0:
                    time.sleep(rem)
                try:
                    dst.sendall(data)
                except OSError:
                    break
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

        threading.Thread(target=sender, daemon=True).start()
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                q.put((time.perf_counter() + self.delay, data))
        except OSError:
            pass
        finally:
            q.put(None)

    def _relay(self, src, dst):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def close(self):
        self.alive = False
        self.listener.close()


def test_pipelining_hides_rtt(server):
    """VERDICT round-1 missing #1: many batched ops must overlap on the
    wire.  Behind a proxy that adds 20 ms one-way latency, N sequential ops
    pay the latency N times; an async flood on one connection pays it ~once.
    This holds regardless of host core count (the round-1 async-vs-sync
    throughput test could not distinguish overlap from CPU contention)."""
    # delay >> scheduling noise: the assertion compares ~N round-trips
    # against ~1, so the margin must survive a loaded single-core host
    delay = 0.05
    N = 8
    proxy = _LatencyProxy(SERVICE_PORT, delay)
    try:
        cfg = ist.ClientConfig(
            host_addr="127.0.0.1", service_port=proxy.port,
            connection_type=ist.TYPE_TCP, log_level="warning",
        )
        conn = ist.InfinityConnection(cfg)
        conn.connect()
        blk = 4096
        buf = np.random.randint(0, 256, size=N * blk, dtype=np.uint8)
        conn.register_mr(buf)

        t0 = time.perf_counter()
        for i in range(N):
            conn.write_cache([(f"rtt-sync-{i}", i * blk)], blk, buf.ctypes.data)
        t_sync = time.perf_counter() - t0

        async def flood():
            await asyncio.gather(*[
                conn.write_cache_async([(f"rtt-async-{i}", i * blk)], blk,
                                       buf.ctypes.data)
                for i in range(N)
            ])

        t0 = time.perf_counter()
        asyncio.run(flood())
        t_async = time.perf_counter() - t0
        conn.close()

        assert t_sync > N * delay * 0.9, t_sync  # sanity: proxy really delays
        # overlapped: far below N round-trips (allow generous scheduling slack)
        assert t_async < t_sync / 2, (t_sync, t_async)
    finally:
        proxy.close()


@pytest.mark.parametrize("client_mode", ["python", "native"])
def test_client_reconnects_after_server_restart(client_mode, monkeypatch):
    """A transport failure mid-session must be survivable: the client tears
    down, reconnects (remapping the restarted server's fresh shm pools,
    replaying MR registrations) and retries the op once — SURVEY §5 failure
    handling, client half."""
    if client_mode == "native":
        from infinistore_tpu import _native

        if not _native.available():
            pytest.skip("native client library not built")
    monkeypatch.setenv("ISTPU_CLIENT", client_mode)
    port, mport = _free_port(), _free_port()

    def boot():
        p = subprocess.Popen(
            [sys.executable, "-m", "infinistore_tpu.server",
             "--service-port", str(port), "--manage-port", str(mport),
             "--prealloc-size", "1", "--minimal-allocate-size", "16",
             "--log-level", "warning", "--backend", "python"],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), timeout=0.5).close()
                return p
            except OSError:
                time.sleep(0.1)
        p.kill()
        raise RuntimeError("server did not start")

    srv = boot()
    try:
        conn = ist.InfinityConnection(ist.ClientConfig(
            host_addr="127.0.0.1", service_port=port,
            connection_type=ist.TYPE_SHM))
        conn.connect()
        src = np.arange(1024, dtype=np.float32)
        dst = np.zeros_like(src)
        conn.register_mr(src)
        conn.register_mr(dst)
        conn.write_cache([("rc-key", 0)], 4096, src.ctypes.data)

        # hard-kill the server (no graceful teardown), then restart it
        srv.kill()
        srv.wait(timeout=10)

        # an op during the outage fails (the reconnect attempt also finds
        # the server down) — but must NOT brick the client: once the server
        # is back, the next op retries the reconnect and succeeds
        with pytest.raises(Exception):
            conn.write_cache([("rc-dead", 0)], 4096, src.ctypes.data)
        srv = boot()

        # the same client object must transparently recover; the restarted
        # store is empty, so the write lands fresh and reads back intact
        conn.write_cache([("rc-key2", 0)], 4096, src.ctypes.data)
        conn.read_cache([("rc-key2", 0)], 4096, dst.ctypes.data)
        np.testing.assert_array_equal(src, dst)
        assert conn.check_exist("rc-key2")
        conn.close()
    finally:
        srv.send_signal(signal.SIGINT)
        try:
            srv.wait(timeout=10)
        except subprocess.TimeoutExpired:
            srv.kill()


# ---- coalesced vs legacy data-plane byte parity ----


def _py_shm_conn(monkeypatch, coalesce: bool):
    """A python-client shm connection with the copy strategy pinned."""
    monkeypatch.setenv("ISTPU_CLIENT", "python")
    conn = make_conn(ist.TYPE_SHM)
    conn.conn.coalesce = coalesce
    return conn


def test_coalesced_vs_legacy_write_parity(server, monkeypatch):
    """The coalesced bulk-copy put and the legacy per-page put must leave
    IDENTICAL pool contents: the same payload written both ways reads back
    byte-equal through the legacy path, the coalesced path, AND the TCP
    inline path (which streams straight out of the pool server-side)."""
    ccon = _py_shm_conn(monkeypatch, True)
    lcon = _py_shm_conn(monkeypatch, False)
    tcon = make_conn(ist.TYPE_TCP)
    nb, blk = 24, 16 << 10
    src = np.random.randint(0, 256, nb * blk, dtype=np.uint8)
    for c in (ccon, lcon):
        c.register_mr(src)
    c_blocks = [(f"par-c-{i}", i * blk) for i in range(nb)]
    l_blocks = [(f"par-l-{i}", i * blk) for i in range(nb)]
    ccon.write_cache(c_blocks, blk, src.ctypes.data)
    lcon.write_cache(l_blocks, blk, src.ctypes.data)
    for reader in (ccon, lcon):
        for blocks in (c_blocks, l_blocks):
            dst = np.zeros_like(src)
            reader.register_mr(dst)
            reader.read_cache(blocks, blk, dst.ctypes.data)
            np.testing.assert_array_equal(src, dst)
    # the TCP view of the pool bytes agrees too
    for key, off in c_blocks[:4] + l_blocks[:4]:
        got = np.asarray(tcon.tcp_read_cache(key))
        np.testing.assert_array_equal(got, src[off : off + blk])
    ccon.close()
    lcon.close()
    tcon.close()


def test_coalesced_read_parity_with_mixed_sizes(server, monkeypatch):
    """Reads over a desc list that CANNOT fully merge (stored sizes below
    the read block size, interleaved pools/offsets) must restore the same
    bytes coalesced and legacy — the degrades-to-per-page path."""
    ccon = _py_shm_conn(monkeypatch, True)
    lcon = _py_shm_conn(monkeypatch, False)
    rng = np.random.RandomState(11)
    blk = 16 << 10
    sizes = [blk, blk // 2, blk, 100, blk, blk // 4]
    payloads = [rng.randint(0, 256, s).astype(np.uint8) for s in sizes]
    keys = [f"mix-{i}" for i in range(len(sizes))]
    for k, p in zip(keys, payloads):
        ccon.conn.w_tcp_bytes(k, p.tobytes())
    blocks = [(k, i * blk) for i, k in enumerate(keys)]
    outs = []
    for reader in (ccon, lcon):
        dst = np.zeros(len(keys) * blk, dtype=np.uint8)
        reader.register_mr(dst)
        reader.read_cache(blocks, blk, dst.ctypes.data)
        outs.append(dst)
    np.testing.assert_array_equal(outs[0], outs[1])
    for i, p in enumerate(payloads):
        np.testing.assert_array_equal(outs[0][i * blk : i * blk + len(p)], p)
    ccon.close()
    lcon.close()


def test_pipelined_write_read_parity(server, monkeypatch):
    """write_cache_pipelined (banded alloc/copy overlap + single commit)
    must be byte-identical to per-band write_cache, and
    read_cache_pipelined must restore the same bytes in band order."""
    conn = _py_shm_conn(monkeypatch, True)
    nb, blk, nbands = 32, 16 << 10, 4
    src = np.random.randint(0, 256, nb * blk, dtype=np.uint8)
    conn.register_mr(src)
    per = nb // nbands
    bands = []
    for b in range(nbands):
        blocks = [(f"pipe-{b}-{i}", i * blk) for i in range(per)]
        base = b * per * blk
        # exercise every src spelling: ptr, array slice, and thunk
        if b % 3 == 0:
            src_spec = src.ctypes.data + base
        elif b % 3 == 1:
            src_spec = src[base : base + per * blk]
        else:
            src_spec = (lambda lo=base, hi=base + per * blk: src[lo:hi])
        bands.append((blocks, blk, src_spec))
    total = conn.write_cache_pipelined(bands)
    assert total == nb * blk
    dst = np.zeros_like(src)
    conn.register_mr(dst)
    order = []
    rbands = [
        (bands[b][0], blk, dst.ctypes.data + b * per * blk)
        for b in range(nbands)
    ]
    got = conn.read_cache_pipelined(rbands, on_band=order.append)
    assert got == nb * blk and order == list(range(nbands))
    np.testing.assert_array_equal(src, dst)
    # legacy reader agrees (pool contents, not just client copy, are right)
    lcon = _py_shm_conn(monkeypatch, False)
    dst2 = np.zeros_like(src)
    lcon.register_mr(dst2)
    for b in range(nbands):
        lcon.read_cache(rbands[b][0], blk, dst2.ctypes.data + b * per * blk)
    np.testing.assert_array_equal(src, dst2)
    lcon.close()
    conn.close()


def test_empty_batch_is_a_noop(server, monkeypatch):
    """Empty block lists return FINISH without a wire round-trip."""
    conn = _py_shm_conn(monkeypatch, True)
    from infinistore_tpu import protocol as P

    assert conn.write_cache([], 4096, 0) == P.FINISH
    assert conn.read_cache([], 4096, 0) == P.FINISH
    assert conn.write_cache_pipelined([]) == 0
    assert conn.read_cache_pipelined([]) == 0
    stats = conn.latency_stats()
    # no alloc/desc round-trip was recorded for the empty calls
    assert stats.get("write_cache.alloc", {}).get("count", 0) == 0
    assert stats.get("read_cache.desc", {}).get("count", 0) == 0
    conn.close()


# ---- disk spill tier, end to end over the wire ----


@pytest.fixture(scope="module", params=["python", "native"])
def tiered_server(request, tmp_path_factory):
    """A server with the SSD/disk spill tier attached (both backends)."""
    service, manage = _free_port(), _free_port()
    tier_dir = str(tmp_path_factory.mktemp(f"disk_tier_{request.param}"))
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(service), "--manage-port", str(manage),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", request.param,
         "--disk-tier-path", tier_dir, "--disk-tier-size", "1"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    _await_ports(proc, (service, manage))
    yield service, manage
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=10)


def test_disk_tier_survives_eviction_over_wire(tiered_server):
    """The full hierarchy over TCP: write, force a FULL eviction (all
    entries spill to disk), then read everything back byte-identical
    through promotion, with the manage plane reporting tier counters."""
    import json
    import urllib.request

    service, manage = tiered_server
    cfg = ist.ClientConfig(host_addr="127.0.0.1", service_port=service,
                           connection_type=ist.TYPE_TCP, log_level="warning")
    conn = ist.InfinityConnection(cfg)
    conn.connect()
    rng = np.random.RandomState(7)
    n, blk = 12, 16 << 10
    buf = rng.randint(0, 256, size=n * blk, dtype=np.uint8)
    conn.register_mr(buf)
    keys = [f"tier-{i}" for i in range(n)]
    conn.write_cache([(k, i * blk) for i, k in enumerate(keys)], blk,
                     buf.ctypes.data)
    # force-evict EVERYTHING (thresholds 0.0): with the tier attached the
    # entries spill instead of vanishing
    conn.evict(0.0, 0.0)
    stats = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{manage}/stats", timeout=10).read())
    assert stats["kvmap_len"] == 0          # DRAM fully drained
    assert stats["disk_entries"] == n       # ...onto the disk tier
    assert stats["disk_spilled"] == n
    # prefix matching still sees the spilled run
    assert conn.get_match_last_index(keys + ["absent"]) == n - 1
    # reads promote back and are byte-identical
    out = np.zeros(n * blk, dtype=np.uint8)
    conn.register_mr(out)
    conn.read_cache([(k, i * blk) for i, k in enumerate(keys)], blk,
                    out.ctypes.data)
    assert np.array_equal(out, buf)
    stats = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{manage}/stats", timeout=10).read())
    assert stats["disk_promoted"] == n
    assert stats["disk_entries"] == 0
    conn.close()


@pytest.fixture(scope="module", params=["python", "native"])
def sizeclass_server(request):
    """A live server running the size-classed allocator (reference
    design.rst:52 "bitmap or jemalloc") on each backend."""
    sport, mport = _free_port(), _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "infinistore_tpu.server",
            "--service-port", str(sport), "--manage-port", str(mport),
            "--prealloc-size", "1", "--minimal-allocate-size", "16",
            "--log-level", "warning", "--backend", request.param,
            "--allocator", "sizeclass",
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    _await_ports(proc, (sport, mport), deadline_s=25)
    yield sport, mport
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_sizeclass_allocator_mixed_sizes_roundtrip(sizeclass_server):
    """Mixed object sizes against the size-classed allocator: small
    (sub-block), mid, and large (multi-class-spanning) values all
    round-trip byte-exact, interleaved deletes don't corrupt neighbors,
    and usage stays sane — the mixed-page-size workload (int8 + bf16
    namespaces) the bitmap allocator fragments on."""
    sport, mport = sizeclass_server
    conn = ist.InfinityConnection(ist.ClientConfig(
        host_addr="127.0.0.1", service_port=sport,
        connection_type=ist.TYPE_SHM,
    ))
    conn.connect()
    try:
        rng = np.random.RandomState(5)
        blobs = {}
        sizes = [100, 4 << 10, 15 << 10, 16 << 10, 60 << 10, 200 << 10]
        for i, size in enumerate(sizes * 3):
            key = f"sc:{i}"
            data = np.frombuffer(rng.bytes(size), dtype=np.uint8).copy()
            conn.tcp_write_cache(key, data.ctypes.data, size)
            blobs[key] = data.tobytes()
        # interleaved deletes, then re-verify every survivor
        victims = [f"sc:{i}" for i in range(0, len(sizes) * 3, 3)]
        assert conn.delete_keys(victims) == len(victims)
        for key, data in blobs.items():
            if key in victims:
                assert not conn.check_exist(key)
            else:
                assert conn.tcp_read_cache(key).tobytes() == data
        # usage reflects a fraction of the BUDGET, not of carved pools
        import json
        import urllib.request

        usage = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/usage", timeout=10).read())
        frac = usage.get("usage", usage)
        if isinstance(frac, dict):
            frac = list(frac.values())[0]
        assert 0.0 < float(frac) < 0.5
    finally:
        conn.close()
