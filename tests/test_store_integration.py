"""End-to-end tests against a live server subprocess.

Mirrors the reference integration suite (infinistore/test_infinistore.py):
a module-scoped server fixture, then every scenario drives the public client
API.  Buffers are numpy arrays standing in for host staging buffers (the JAX
HBM paths are covered in test_kv.py).
"""

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time
from multiprocessing import Process

import numpy as np
import pytest

import infinistore_tpu as ist


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


SERVICE_PORT = _free_port()
MANAGE_PORT = _free_port()


@pytest.fixture(scope="module")
def server():
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "infinistore_tpu.server",
            "--service-port",
            str(SERVICE_PORT),
            "--manage-port",
            str(MANAGE_PORT),
            "--prealloc-size",
            "1",
            "--minimal-allocate-size",
            "16",
            "--log-level",
            "warning",
            "--backend",
            "python",
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    # wait for the data plane to accept connections
    deadline = time.time() + 15
    while time.time() < deadline:
        if proc.poll() is not None:
            pytest.fail("server process failed to start")
        try:
            socket.create_connection(("127.0.0.1", SERVICE_PORT), timeout=0.5).close()
            break
        except OSError:
            time.sleep(0.1)
    else:
        pytest.fail("server did not come up")
    yield proc
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def make_conn(connection_type=ist.TYPE_SHM):
    config = ist.ClientConfig(
        host_addr="127.0.0.1",
        service_port=SERVICE_PORT,
        connection_type=connection_type,
    )
    conn = ist.InfinityConnection(config)
    conn.connect()
    return conn


def rand_key(n=10):
    import random
    import string

    return "".join(random.choice(string.ascii_letters + string.digits) for _ in range(n))


@pytest.mark.parametrize("dtype", [np.float16, np.float32])
def test_basic_read_write_cache(server, dtype):
    """Reference parity: test_basic_read_write_cache."""
    conn = make_conn()
    key = rand_key()
    src = np.arange(4096, dtype=dtype)
    conn.register_mr(src)
    esize = src.itemsize

    asyncio.run(conn.write_cache_async([(key, 0)], 4096 * esize, src.ctypes.data))
    conn.close()

    conn = make_conn()
    dst = np.zeros(4096, dtype=dtype)
    conn.register_mr(dst)
    asyncio.run(conn.read_cache_async([(key, 0)], 4096 * esize, dst.ctypes.data))
    np.testing.assert_array_equal(src, dst)
    conn.close()


@pytest.mark.parametrize("connection_type", [ist.TYPE_SHM, ist.TYPE_TCP])
def test_batch_read_write_cache(server, connection_type):
    """Reference parity: test_batch_read_write_cache (both transports)."""
    conn = make_conn(connection_type)
    num_blocks, block_elems = 10, 4096
    src = np.arange(num_blocks * block_elems, dtype=np.float32)
    conn.register_mr(src)

    async def run():
        for _ in range(3):
            keys = [rand_key() for _ in range(num_blocks)]
            blocks = [(keys[i], i * block_elems * 4) for i in range(num_blocks)]
            await conn.write_cache_async(blocks, block_elems * 4, src.ctypes.data)
            dst = np.zeros(num_blocks * block_elems, dtype=np.float32)
            conn.register_mr(dst)
            await conn.read_cache_async(blocks, block_elems * 4, dst.ctypes.data)
            np.testing.assert_array_equal(src, dst)

    asyncio.run(run())
    conn.close()


def _client_roundtrip(port):
    config = ist.ClientConfig(
        host_addr="127.0.0.1", service_port=port, connection_type=ist.TYPE_SHM
    )
    conn = ist.InfinityConnection(config)
    conn.connect()
    key = rand_key()
    src = np.arange(4096, dtype=np.float32)
    conn.register_mr(src)
    asyncio.run(conn.write_cache_async([(key, 0)], 4096 * 4, src.ctypes.data))
    conn.close()

    conn = ist.InfinityConnection(config)
    conn.connect()
    dst = np.zeros(4096, dtype=np.float32)
    conn.register_mr(dst)
    asyncio.run(conn.read_cache_async([(key, 0)], 4096 * 4, dst.ctypes.data))
    np.testing.assert_array_equal(src, dst)
    conn.close()


def test_multiple_clients(server):
    """Reference parity: test_multiple_clients."""
    procs = [Process(target=_client_roundtrip, args=(SERVICE_PORT,)) for _ in range(2)]
    for p in procs:
        p.start()
    for p in procs:
        p.join()
        assert p.exitcode == 0


def test_key_check(server):
    conn = make_conn()
    key = rand_key(5)
    src = np.random.randn(4096).astype(np.float32)
    conn.register_mr(src)
    asyncio.run(conn.write_cache_async([(key, 0)], 4096 * 4, src.ctypes.data))
    assert conn.check_exist(key)
    assert not conn.check_exist("definitely_missing")
    conn.close()


def test_get_match_last_index(server):
    """Reference parity: test_get_match_last_index."""
    conn = make_conn()
    src = np.random.randn(4096).astype(np.float32)
    conn.register_mr(src)
    asyncio.run(
        conn.write_cache_async(
            [("key1", 0), ("key2", 1024), ("key3", 2048)], 1024 * 4, src.ctypes.data
        )
    )
    assert conn.get_match_last_index(["A", "B", "C", "key1", "D", "E"]) == 3
    conn.close()


def test_get_match_no_match_raises(server):
    conn = make_conn()
    with pytest.raises(ist.InfiniStoreException):
        conn.get_match_last_index(["zzz_no", "zzz_way"])
    conn.close()


def test_key_not_found(server):
    """Reference parity: test_key_not_found / test_read_non_exist_key."""
    conn = make_conn()
    dst = np.zeros(4096, dtype=np.float32)
    conn.register_mr(dst)
    with pytest.raises(ist.InfiniStoreKeyNotFound):
        asyncio.run(
            conn.read_cache_async([("non_exist_key", 0)], 4096 * 4, dst.ctypes.data)
        )
    conn.close()


def test_upload_one_conn_download_another(server):
    """Reference parity: test_upload_cpu_download_gpu."""
    src_conn = make_conn()
    dst_conn = make_conn()
    key = rand_key(5)
    src = np.random.randn(4096).astype(np.float32)
    dst = np.zeros(4096, dtype=np.float32)
    src_conn.register_mr(src)
    dst_conn.register_mr(dst)

    async def run():
        await src_conn.write_cache_async([(key, 0)], 4096 * 4, src.ctypes.data)
        await dst_conn.read_cache_async([(key, 0)], 4096 * 4, dst.ctypes.data)

    asyncio.run(run())
    np.testing.assert_array_equal(src, dst)
    src_conn.close()
    dst_conn.close()


def test_async_api(server):
    """Reference parity: test_async_api (connect_async + awaited ops)."""
    config = ist.ClientConfig(
        host_addr="127.0.0.1",
        service_port=SERVICE_PORT,
        connection_type=ist.TYPE_SHM,
    )
    conn = ist.InfinityConnection(config)

    async def run():
        await conn.connect_async()
        key = rand_key(5)
        src = np.random.randn(4096).astype(np.float32)
        dst = np.zeros(4096, dtype=np.float32)
        conn.register_mr(src)
        conn.register_mr(dst)
        await conn.write_cache_async([(key, 0)], 4096 * 4, src.ctypes.data)
        await conn.read_cache_async([(key, 0)], 4096 * 4, dst.ctypes.data)
        np.testing.assert_array_equal(src, dst)
        conn.close()

    asyncio.run(run())


def test_delete_keys(server):
    """Reference parity: test_delete_keys."""
    conn = make_conn()
    src = np.random.randn(4096).astype(np.float32)
    keys = [rand_key() for _ in range(3)]
    conn.register_mr(src)
    asyncio.run(
        conn.write_cache_async(
            [(keys[i], i * 1024 * 4) for i in range(3)], 1024 * 4, src.ctypes.data
        )
    )
    for k in keys:
        assert conn.check_exist(k)
    assert conn.delete_keys([keys[0], keys[2]]) == 2
    assert conn.check_exist(keys[1])
    assert not conn.check_exist(keys[0])
    assert not conn.check_exist(keys[2])
    conn.close()


def test_simple_tcp_read_write(server):
    """Reference parity: test_simple_tcp_read_write."""
    conn = make_conn(ist.TYPE_TCP)
    key = rand_key()
    size = 256 * 1024
    src = np.arange(size, dtype=np.uint8) % 200
    conn.tcp_write_cache(key, src.ctypes.data, size)
    dst = conn.tcp_read_cache(key)
    np.testing.assert_array_equal(np.asarray(dst), src)
    conn.close()


def test_overwrite_tcp(server):
    """Reference parity: test_overwrite_tcp."""
    conn = make_conn(ist.TYPE_TCP)
    key = rand_key()
    size = 256 * 1024
    src = np.arange(size, dtype=np.uint8) % 200
    conn.tcp_write_cache(key, src.ctypes.data, size)
    src2 = np.arange(size, dtype=np.uint8) % 100
    conn.tcp_write_cache(key, src2.ctypes.data, size)
    dst = conn.tcp_read_cache(key)
    np.testing.assert_array_equal(np.asarray(dst), src2)
    conn.close()


def test_manage_plane(server):
    import json
    import urllib.request

    with urllib.request.urlopen(
        f"http://127.0.0.1:{MANAGE_PORT}/selftest", timeout=5
    ) as r:
        assert json.load(r)["status"] == "ok"
    with urllib.request.urlopen(
        f"http://127.0.0.1:{MANAGE_PORT}/kvmap_len", timeout=5
    ) as r:
        assert json.load(r)["len"] >= 0
    with urllib.request.urlopen(
        f"http://127.0.0.1:{MANAGE_PORT}/metrics", timeout=5
    ) as r:
        m = json.load(r)
    assert "usage" in m and "puts" in m


def test_purge_via_manage_plane(server):
    import json
    import urllib.request

    conn = make_conn()
    src = np.ones(1024, dtype=np.float32)
    conn.register_mr(src)
    key = rand_key()
    asyncio.run(conn.write_cache_async([(key, 0)], 1024 * 4, src.ctypes.data))
    assert conn.check_exist(key)
    req = urllib.request.Request(
        f"http://127.0.0.1:{MANAGE_PORT}/purge", method="POST"
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        assert json.load(r)["status"] == "ok"
    assert not conn.check_exist(key)
    conn.close()


def test_concurrent_async_writers_one_connection(server):
    """Many in-flight async ops on one connection must not corrupt frames."""
    conn = make_conn()
    src = np.arange(64 * 1024, dtype=np.float32)
    conn.register_mr(src)

    async def run():
        tasks = []
        for j in range(16):
            blocks = [(f"cc{j}_{i}", i * 4096) for i in range(8)]
            tasks.append(conn.write_cache_async(blocks, 4096, src.ctypes.data))
        await asyncio.gather(*tasks)
        dst = np.zeros_like(src)
        conn.register_mr(dst)
        reads = []
        for j in range(16):
            blocks = [(f"cc{j}_{i}", i * 4096) for i in range(8)]
            reads.append(conn.read_cache_async(blocks, 4096, dst.ctypes.data))
        await asyncio.gather(*reads)
        np.testing.assert_array_equal(dst[: 8 * 1024], src[: 8 * 1024])

    asyncio.run(run())
    conn.close()
