"""End-to-end KV-cache integrity: checksummed pages, epoch-fenced
descriptors, corruption injection and the background scrubber.

The contract under test (docs/robustness.md §5): silent garbage —
a region recycled behind an expired read lease, a torn write, a fault-
flipped pool byte, a pool mapping that predates a server restart — is
always DETECTED and served as a cache miss (recompute), never delivered
into the paged cache or surfaced as a failed request.  Corruption is
driven deterministically through the ``FaultInjector``'s ``corrupt``
action, never by poking /dev/shm and hoping.
"""

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import infinistore_tpu as ist
from infinistore_tpu import protocol as P
from infinistore_tpu.utils import checksum as C
from infinistore_tpu.utils import metrics as m


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _boot(port, mport, extra_env=None, extra_args=()):
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", "python", *extra_args],
        env={**os.environ, "JAX_PLATFORMS": "cpu", **(extra_env or {})},
    )
    deadline = time.time() + 25
    for p in (port, mport):
        while True:
            if proc.poll() is not None:
                pytest.fail("server process failed to start")
            try:
                socket.create_connection(("127.0.0.1", p), timeout=0.5).close()
                break
            except OSError:
                if time.time() >= deadline:
                    proc.kill()
                    pytest.fail(f"server port {p} did not come up")
                time.sleep(0.1)
    return proc


def _stop(proc):
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def _arm(mport, rules):
    req = urllib.request.Request(
        f"http://127.0.0.1:{mport}/faults", method="POST",
        data=json.dumps(rules).encode(),
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.load(r)


def _integrity(mport):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mport}/debug/integrity", timeout=10
    ) as r:
        return json.load(r)


def _wait_stamped(mport, timeout=10.0):
    """Block until the stamping backlog drained (every committed entry
    carries a checksum) — corruption tests arm faults only after this,
    so detection is deterministic, not racing the integrity worker."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        rep = _integrity(mport)
        if rep["unverified"] == 0 and rep["stamp_backlog"] == 0:
            return rep
        time.sleep(0.05)
    pytest.fail("stamping backlog did not drain")


def _store_metrics(mport):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mport}/metrics", timeout=10
    ) as r:
        return m.parse_prometheus_text(r.read().decode())


def _conn(port, ctype=ist.TYPE_SHM, op_timeout_s=5.0, **kw):
    # op_timeout_s pins the PYTHON client: the integrity plane lives in
    # its channel layer (the native C client never negotiates it)
    c = ist.InfinityConnection(ist.ClientConfig(
        host_addr="127.0.0.1", service_port=port, connection_type=ctype,
        log_level="error", op_timeout_s=op_timeout_s, **kw,
    ))
    c.connect()
    return c


def _failures(cause):
    parsed = m.parse_prometheus_text(
        m.default_registry().to_prometheus_text()
    )
    return parsed.get(
        ("istpu_integrity_failures_total", (("cause", cause),)), 0.0
    )


@pytest.fixture(scope="module")
def server():
    port, mport = _free_port(), _free_port()
    proc = _boot(port, mport)
    yield port, mport
    _stop(proc)


@pytest.fixture(autouse=True)
def _clear_faults(server):
    yield
    try:
        _arm(server[1], [])
    except OSError:
        pass


# ---- checksum + protocol units (no server) ----


def test_checksum_algorithms_agree_and_detect_flips():
    data = np.random.randint(0, 256, 64 << 10, dtype=np.uint8)
    for alg in (C.ALG_SUM64, C.ALG_CRC32):
        ref = C.checksum(data, alg)
        assert ref == C.checksum(bytes(data), alg)  # buffer-kind agnostic
        flipped = data.copy()
        flipped[12345] ^= 0x01  # a single bit
        assert C.checksum(flipped, alg) != ref
        # the row-vectorized path must agree bit-for-bit with the scalar
        rows = data.reshape(4, 16 << 10)
        assert C.checksum_rows(rows, alg) == [
            C.checksum(rows[i], alg) for i in range(4)
        ]
    # scalar sum64 handles non-8-aligned tails
    odd = data[: (16 << 10) + 3]
    assert C.checksum(odd) != C.checksum(odd[:-1])


def test_protocol_epoch_trailer_and_desc_ex_roundtrip():
    pools = [("istpu_pool_0", 1 << 20, 16 << 10)]
    legacy = P.pack_pool_table(pools)
    # legacy body: no epoch, and the 3-tuple hello parser is untouched
    assert P.unpack_hello_epoch(memoryview(legacy)) is None
    # EPOC alone, and EPOC behind a TRAC trailer, both resolve; the
    # legacy pool-table parser ignores every trailer byte either way
    for body in (
        legacy + P.pack_epoch_trailer(C.ALG_SUM64, 777),
        legacy + P.pack_hello_trailer(P.HELLO_FLAG_TRACE_CTX, 1.5)
        + P.pack_epoch_trailer(C.ALG_CRC32, 888),
    ):
        assert P.unpack_pool_table(memoryview(body)) == pools
        alg, epoch = P.unpack_hello_epoch(memoryview(body))
        assert (alg, epoch) in ((C.ALG_SUM64, 777), (C.ALG_CRC32, 888))
    got_pools, flags, t = P.unpack_hello_resp(
        memoryview(legacy + P.pack_epoch_trailer(1, 9)))
    assert got_pools == pools and flags == 0  # EPOC != TRAC for old logic

    descs = [(0, 0, 4096, 123), (1, 1 << 33, 65536, None)]
    buf = P.pack_desc_resp_ex(42, descs)
    epoch, out = P.unpack_desc_resp_ex(memoryview(buf))
    assert epoch == 42 and out == descs
    # inline ex prefix + batch items
    epoch, csum, consumed = P.unpack_inline_resp_ex(
        memoryview(P.pack_inline_resp_ex(7, None) + b"xy"))
    assert (epoch, csum, consumed) == (7, None, P.INLINE_EX_SIZE)
    items = P.pack_batch_item_ex(10, 5) + P.pack_batch_item_ex(20, None)
    assert P.unpack_batch_items_ex(memoryview(items), 2) == [
        (10, 5), (20, None)]


# ---- store units (hand-built store, injectable clock) ----


def _unit_store():
    from test_store_unit import make_store

    return make_store()


def test_store_stamps_verifies_and_quarantines():
    s = _unit_store()
    try:
        s.put_inline(b"k", b"hello world" * 100)
        e = s.kv[b"k"]
        assert e.crc is None  # stamping is deferred off the commit path
        assert s.stamp_pending() == 1
        assert e.crc is not None and s.verify_entry(b"k", e) is True
        # flip a committed byte: verify fails, scrub quarantines
        s.mm.view(e.pool_idx, e.offset, e.size)[0] ^= 0xFF
        assert s.verify_entry(b"k", e) is False
        scanned, corrupt = s.scrub_step()
        assert scanned == 1 and corrupt == 1
        assert b"k" not in s.kv and s.stats.scrub_corrupt == 1
        assert s.integrity_report()["quarantined"] == 1
    finally:
        s.close()


def test_scrub_skips_leased_and_stamps_backlog():
    s = _unit_store()
    try:
        now = [1000.0]
        s._clock = lambda: now[0]
        s.put_inline(b"a", b"x" * 4096)
        s.put_inline(b"b", b"y" * 4096)
        st, _ = s.get_desc([b"a"])  # leases 'a'
        assert st == P.FINISH
        scanned, corrupt = s.scrub_step()
        # 'a' is under a live lease -> skipped; 'b' gets first-stamped
        assert scanned == 1 and corrupt == 0
        assert s.kv[b"b"].crc is not None and s.kv[b"a"].crc is None
        now[0] += 10.0  # lease expires -> next pass reaches 'a'
        s.scrub_step()
        assert s.kv[b"a"].crc is not None
    finally:
        s.close()


def test_quarantine_defers_free_under_live_lease():
    s = _unit_store()
    try:
        now = [1000.0]
        s._clock = lambda: now[0]
        s.put_inline(b"k", b"z" * 4096)
        s.get_desc([b"k"])  # an shm reader may be mid-copy
        assert s.quarantine(b"k")
        assert b"k" not in s.kv  # key gone immediately (reads must miss)
        assert len(s._deferred) == 1  # blocks still pinned for the reader
        now[0] += 10.0
        s._reap_deferred(now[0])
        assert not s._deferred
    finally:
        s.close()


def test_release_desc_clears_lease_only_at_zero_readers():
    s = _unit_store()
    try:
        now = [1000.0]
        s._clock = lambda: now[0]
        s.put_inline(b"k", b"q" * 4096)
        s.get_desc([b"k"])
        s.get_desc([b"k"])  # two concurrent readers
        e = s.kv[b"k"]
        assert e.readers == 2 and e.lease > now[0]
        assert s.release_desc([b"k"]) == 0  # one reader still holds it
        assert e.lease > now[0]
        assert s.release_desc([b"k"]) == 1  # last reader out
        assert e.lease == 0.0 and s.active_leases() == 0
        # releasing an unleased / unknown key is a no-op
        assert s.release_desc([b"k", b"nope"]) == 0
        # a lease that expired naturally resets the reader count on the
        # next grant (legacy clients never release)
        s.get_desc([b"k"])
        now[0] += 10.0
        s.get_desc([b"k"])
        assert s.kv[b"k"].readers == 1
    finally:
        s.close()


# ---- wire: verification, release, corruption, epoch fencing ----


def test_shm_read_verifies_and_releases_lease_early(server):
    port, mport = server
    conn = _conn(port)
    assert conn.conn.integrity and conn.conn.epoch is not None
    blk, n = 16 << 10, 8
    src = np.random.randint(0, 256, n * blk, dtype=np.uint8)
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)
    blocks = [(f"rel-{i}", i * blk) for i in range(n)]
    conn.write_cache(blocks, blk, src.ctypes.data)
    _wait_stamped(mport)
    conn.read_cache(blocks, blk, dst.ctypes.data)
    np.testing.assert_array_equal(src, dst)
    # the satellite contract: verified copies hand their leases back NOW,
    # not after the 5 s timed lease (which fragmented back-to-back bench
    # runs); poll briefly — the release is a fire-and-forget frame
    deadline = time.time() + 2.0
    while time.time() < deadline:
        if _store_metrics(mport).get(
                ("istpu_store_active_read_leases", ()), 0) == 0:
            break
        time.sleep(0.05)
    assert _store_metrics(mport)[
        ("istpu_store_active_read_leases", ())] == 0
    conn.close()


def test_corrupt_fault_is_detected_and_counted(server):
    port, mport = server
    conn = _conn(port)
    blk, n = 16 << 10, 4
    src = np.random.randint(0, 256, n * blk, dtype=np.uint8)
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)
    blocks = [(f"cor-{i}", i * blk) for i in range(n)]
    conn.write_cache(blocks, blk, src.ctypes.data)
    _wait_stamped(mport)
    before = _failures("checksum")
    _arm(mport, [{"op": "GET_DESC", "action": "corrupt", "times": 1}])
    with pytest.raises(ist.InfiniStoreIntegrityError) as ei:
        conn.read_cache(blocks, blk, dst.ctypes.data)
    assert ei.value.cause == "checksum" and ei.value.keys
    assert _failures("checksum") == before + 1
    # the injected corruption is visible in the fault counter too
    assert _store_metrics(mport)[
        ("istpu_store_faults_injected_total",
         (("action", "corrupt"), ("op", "GET_DESC")))] >= 1
    conn.close()


def test_corrupt_inline_get_detected_over_tcp(server):
    port, mport = server
    conn = _conn(port, ctype=ist.TYPE_TCP)
    payload = np.random.randint(0, 256, 4096, dtype=np.uint8)
    conn.register_mr(payload)
    conn.tcp_write_cache("tcp-cor", payload.ctypes.data, payload.nbytes)
    _wait_stamped(mport)
    assert conn.tcp_read_cache("tcp-cor").tobytes() == payload.tobytes()
    _arm(mport, [{"op": "GET_INLINE", "action": "corrupt", "times": 1}])
    with pytest.raises(ist.InfiniStoreIntegrityError):
        conn.tcp_read_cache("tcp-cor")
    conn.close()


def test_epoch_fence_invalidates_read_and_remaps(server):
    """A client whose captured epoch no longer matches the server's must
    fail the read closed (cause=epoch), drop its pool attach, remap, and
    recover on the next op."""
    port, mport = server
    conn = _conn(port)
    raw = conn.conn
    blk = 16 << 10
    src = np.random.randint(0, 256, blk, dtype=np.uint8)
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)
    conn.write_cache([("ep-0", 0)], blk, src.ctypes.data)
    before = _failures("epoch")
    raw.epoch -= 1  # simulate state captured from a pre-restart server
    with pytest.raises(ist.InfiniStoreIntegrityError) as ei:
        conn.read_cache([("ep-0", 0)], blk, dst.ctypes.data)
    assert ei.value.cause == "epoch"
    assert _failures("epoch") == before + 1
    assert raw.epoch is not None and raw.pools  # resynced + remapped
    conn.read_cache([("ep-0", 0)], blk, dst.ctypes.data)  # recovered
    np.testing.assert_array_equal(src, dst)
    conn.close()


def test_store_restart_fences_stale_clients_fail_closed():
    """Kill → restart behind auto-reconnect: the reconnected client must
    observe the NEW epoch (counted as an epoch fence), map the NEW pools,
    and answer reads of pre-restart keys with a clean miss — never bytes
    from a recycled pool."""
    port, mport = _free_port(), _free_port()
    proc = _boot(port, mport)
    conn = _conn(port, op_timeout_s=2.0, auto_reconnect=True)
    epoch0 = conn.conn.epoch
    assert epoch0 is not None
    blk = 16 << 10
    src = np.random.randint(0, 256, blk, dtype=np.uint8)
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)
    conn.write_cache([("fence-0", 0)], blk, src.ctypes.data)
    conn.read_cache([("fence-0", 0)], blk, dst.ctypes.data)
    np.testing.assert_array_equal(src, dst)

    proc.kill()  # hard kill: no goodbye, shm unlinked by the sweeper
    proc.wait(timeout=10)
    proc = _boot(port, mport)
    before = _failures("epoch")

    # the first op fails over the dead socket, reconnects, and lands on
    # the restarted (empty) store: fail-closed KeyNotFound, NEVER stale
    # bytes out of a recycled pool
    dst[:] = 0
    with pytest.raises(ist.InfiniStoreKeyNotFound):
        conn.read_cache([("fence-0", 0)], blk, dst.ctypes.data)
    assert not dst.any(), "stale bytes delivered across a restart"
    assert conn.conn.epoch != epoch0  # the new boot epoch was captured
    assert _failures("epoch") >= before + 1  # the fence was counted
    # and the fresh epoch serves normally
    conn.write_cache([("fence-1", 0)], blk, src.ctypes.data)
    conn.read_cache([("fence-1", 0)], blk, dst.ctypes.data)
    np.testing.assert_array_equal(src, dst)
    conn.close()
    _stop(proc)


# ---- the background scrubber (live, level=scrub) ----


def test_scrubber_quarantines_corrupt_entries_live():
    port, mport = _free_port(), _free_port()
    proc = _boot(port, mport, extra_args=("--integrity", "scrub",
                                          "--scrub-rate", "5000"))
    conn = _conn(port)
    blk, n = 16 << 10, 8
    src = np.random.randint(0, 256, n * blk, dtype=np.uint8)
    conn.register_mr(src)
    blocks = [(f"scr-{i}", i * blk) for i in range(n)]
    conn.write_cache(blocks, blk, src.ctypes.data)
    _wait_stamped(mport)
    # flip bytes in ONE entry via the corrupt fault (EXIST names the key
    # without reading it, so nothing verifies client-side first)
    _arm(mport, [{"op": "EXIST", "action": "corrupt", "times": 1}])
    assert conn.check_exist("scr-3") is True
    _arm(mport, [])
    deadline = time.time() + 15
    while time.time() < deadline:
        rep = _integrity(mport)
        if rep["scrub_corrupt"] >= 1:
            break
        time.sleep(0.05)
    assert rep["scrub_corrupt"] == 1 and rep["quarantined"] == 1, rep
    assert rep["scrub_pages"] >= 1
    # quarantined = the key disappeared; the other entries still serve
    assert conn.check_exist("scr-3") is False
    dst = np.zeros(blk, dtype=np.uint8)
    conn.register_mr(dst)
    with pytest.raises(ist.InfiniStoreKeyNotFound):
        conn.read_cache([("scr-3", 0)], blk, dst.ctypes.data)
    conn.read_cache([("scr-0", 0)], blk, dst.ctypes.data)
    np.testing.assert_array_equal(src[:blk], dst)
    # both scrub families are on /metrics for alerting
    parsed = _store_metrics(mport)
    assert parsed[("istpu_store_scrub_corrupt_total", ())] == 1
    assert parsed[("istpu_store_scrub_pages_total", ())] >= 1
    conn.close()
    _stop(proc)


# ---- corruption chaos under the serving stack ----


jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from infinistore_tpu.engine import InferenceEngine  # noqa: E402
from infinistore_tpu.kv import PagedCacheConfig  # noqa: E402
from infinistore_tpu.models import TINY, init_params, scaled  # noqa: E402
from infinistore_tpu.serve import ServingServer  # noqa: E402

from conftest import make_dense_greedy  # noqa: E402

CFG = scaled(TINY, dtype=jnp.float32)
PARAMS = init_params(CFG, jax.random.PRNGKey(7))
T = 4
PROMPT = [11, 42, 7, 99, 5, 3, 17, 28, 64, 1, 2]

dense_greedy = make_dense_greedy(PARAMS, CFG)


def make_pc(n_blocks=128):
    return PagedCacheConfig(
        n_layers=CFG.n_layers, n_kv_heads=CFG.n_kv_heads,
        head_dim=CFG.head_dim, n_blocks=n_blocks, block_tokens=T,
        dtype=CFG.dtype,
    )


def _prompt(i):
    """Distinct same-length prompts (first token varies): repeated
    prompts would hit the engine's LOCAL prefix cache and never touch
    the store (the PR-3 chaos-test trap)."""
    assert i < 450, i
    return [50 + i] + PROMPT[1:]


def test_guarded_load_treats_verification_failure_as_miss(server):
    """Engine level: a corrupt store prefix degrades to recompute with
    byte-exact tokens; the failed pages are deleted (client-assisted
    quarantine) so the NEXT request misses cleanly and repopulates."""
    port, mport = server
    prod = _conn(port, op_timeout_s=5.0)
    a = InferenceEngine(PARAMS, CFG, make_pc(), conn=prod,
                        model_id="integ-eng")
    a.release(a.prefill(_prompt(0)))
    a.store_flush()
    _wait_stamped(mport)

    cons = _conn(port, op_timeout_s=5.0)
    b = InferenceEngine(PARAMS, CFG, make_pc(), conn=cons,
                        model_id="integ-eng")
    before = _failures("checksum") + _failures("lease")
    _arm(mport, [{"op": "GET_DESC", "action": "corrupt", "times": 1}])
    st = b.prefill(_prompt(0))  # store hit found, load fails verification
    assert st.reused_chunks == 0  # withdrawn -> full recompute
    assert b.decode(st, 8) == dense_greedy(_prompt(0), 8)
    b.release(st)
    assert _failures("checksum") + _failures("lease") >= before + 1
    assert b.breaker.state == "closed"  # bad bytes never trip the circuit
    _arm(mport, [])
    # self-healing: the failed pages were deleted (client-assisted
    # quarantine) and b's recompute re-pushed FRESH pages under the same
    # content-addressed keys — a new consumer reuses them and still
    # decodes byte-exact, proving the corruption never survived
    _wait_stamped(mport)
    c2 = _conn(port, op_timeout_s=5.0)
    eng2 = InferenceEngine(PARAMS, CFG, make_pc(), conn=c2,
                           model_id="integ-eng")
    st2 = eng2.prefill(_prompt(0))
    assert st2.reused_chunks == 2  # repopulated after the quarantine
    assert eng2.decode(st2, 8) == dense_greedy(_prompt(0), 8)
    eng2.release(st2)
    prod.close()
    cons.close()
    c2.close()


@pytest.fixture(scope="module")
def chaos_stack():
    port, mport = _free_port(), _free_port()
    proc = _boot(port, mport)
    conn = _conn(port, op_timeout_s=2.0)
    eng = InferenceEngine(
        PARAMS, CFG, make_pc(n_blocks=128), conn=conn,
        model_id="integ-serve", store_durability="relaxed",
    )
    eng.decode_chunk = 4
    srv = ServingServer(eng, port=0, max_batch=4, model_id="integ-serve")
    srv.start()
    yield srv, proc, port, mport
    srv.close()
    conn.close()
    _stop(proc)


def _post(port, body, timeout=180, path="/v1/completions"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data)


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_corruption_chaos_serving_stays_byte_exact(chaos_stack):
    """THE acceptance chaos test: with bit-flip faults armed against the
    live store, every request still answers 200 with byte-exact greedy
    tokens (corrupt pages degrade to recompute and are NEVER admitted
    into the paged cache), and the client-side failure counter walks."""
    srv, proc, port, mport = chaos_stack
    n = [300]

    def ask(prompt=None):
        p = prompt if prompt is not None else _prompt(n[0])
        if prompt is None:
            n[0] += 1
        status, body = _post(srv.port, {
            "prompt": p, "max_tokens": 6, "temperature": 0,
        })
        assert status == 200, body
        # byte-exact greedy tokens == zero corrupt pages reached attention
        assert body["choices"][0]["token_ids"] == dense_greedy(p, 6), body
        return body

    # phase 0: healthy; a producer seeds a store-resident prefix the
    # serving engine has never seen locally
    ask()
    prod_conn = _conn(port, op_timeout_s=5.0)
    prod = InferenceEngine(PARAMS, CFG, make_pc(), conn=prod_conn,
                           model_id="integ-serve")
    victims = [_prompt(400 + i) for i in range(3)]
    for v in victims:
        prod.release(prod.prefill(v))
    prod.store_flush()
    _wait_stamped(mport)

    # phase 1: every GET_DESC frame corrupts the pages it asks for —
    # each victim's store hit fails verification and recomputes
    before = _failures("checksum") + _failures("lease")
    _arm(mport, [{"op": "GET_DESC", "action": "corrupt"}])
    for v in victims:
        ask(v)          # store prefix found, corrupted, detected, recomputed
    for _ in range(3):
        ask()           # fresh prompts keep serving normally through it
    assert _failures("checksum") + _failures("lease") > before
    # the store counted the injected corruption deterministically
    assert _store_metrics(mport).get(
        ("istpu_store_faults_injected_total",
         (("action", "corrupt"), ("op", "GET_DESC"))), 0) >= 1

    # phase 2: faults cleared — victims now hit again; recompute pushed
    # fresh (valid) pages under the same content-addressed keys, so
    # serving returns to store-accelerated with byte parity intact
    _arm(mport, [])
    for v in victims:
        ask(v)
    st, data = _get(srv.port, "/healthz")
    assert st == 200 and json.loads(data)["status"] == "ok"
    # the failure breakdown is scrapeable from the serving /metrics
    st, data = _get(srv.port, "/metrics")
    parsed = m.parse_prometheus_text(data.decode())
    total_fail = sum(
        v for (name, _l), v in parsed.items()
        if name == "istpu_integrity_failures_total"
    )
    assert total_fail >= 1
    prod_conn.close()
