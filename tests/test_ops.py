"""Pallas kernels vs XLA reference math (interpret mode on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from infinistore_tpu.models.attention import paged_decode_attention_xla
from infinistore_tpu.ops import paged_decode_attention_pallas


def _setup(B, H, Hkv, D, T, n_blocks, max_pages, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, H, D)), dtype)
    # serving layout == kernel layout: [2, H_kv, n_blocks, T, D]
    cache = jnp.asarray(rng.standard_normal((2, Hkv, n_blocks, T, D)), dtype)
    # each sequence gets distinct pages; lengths straddle page boundaries
    table = np.zeros((B, max_pages), dtype=np.int32)
    lens = np.zeros((B,), dtype=np.int32)
    free = list(range(1, n_blocks))
    for b in range(B):
        n_tok = int(rng.integers(1, max_pages * T))
        n_pages = -(-n_tok // T)
        ids = [free.pop() for _ in range(n_pages)]
        table[b, :n_pages] = ids
        lens[b] = n_tok
    return q, cache, jnp.asarray(table), jnp.asarray(lens)


@pytest.mark.parametrize("n_rep", [1, 4])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_paged_decode_kernel_matches_xla(n_rep, dtype):
    Hkv, D, T = 2, 128, 16
    B, max_pages, n_blocks = 3, 4, 16
    q, cache, table, lens = _setup(
        B, Hkv * n_rep, Hkv, D, T, n_blocks, max_pages, dtype=dtype
    )
    want = paged_decode_attention_xla(q, cache, table, lens)
    got = paged_decode_attention_pallas(q, cache, table, lens, interpret=True)
    tol = 5e-6 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=tol, atol=tol,
    )


def test_paged_decode_kernel_single_token():
    # seq_len == 1: only the first slot of the first page is valid
    Hkv, D, T = 2, 128, 16
    q, cache, table, lens = _setup(1, 8, Hkv, D, T, 8, 2)
    lens = jnp.asarray([1], jnp.int32)
    want = paged_decode_attention_xla(q, cache, table, lens)
    got = paged_decode_attention_pallas(q, cache, table, lens, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=5e-6, atol=5e-6
    )


# ---- flash causal prefill kernel ----

from infinistore_tpu.models.attention import causal_attention  # noqa: E402
from infinistore_tpu.ops import flash_causal_attention_pallas  # noqa: E402


def _flash_setup(B, Sq, Sk, H, Hkv, D, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, Sq, H, D)), dtype)
    k = jnp.asarray(rng.standard_normal((B, Sk, Hkv, D)), dtype)
    v = jnp.asarray(rng.standard_normal((B, Sk, Hkv, D)), dtype)
    return q, k, v


@pytest.mark.parametrize("n_rep", [1, 4])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_flash_prefill_matches_xla(n_rep, dtype):
    B, S, Hkv, D = 2, 48, 2, 128  # S straddles block boundaries after padding
    q, k, v = _flash_setup(B, S, S, Hkv * n_rep, Hkv, D, dtype=dtype)
    want = causal_attention(q, k, v)
    got = flash_causal_attention_pallas(
        q, k, v, interpret=True, block_q=16, block_k=16
    )
    tol = 5e-6 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=tol, atol=tol,
    )


def test_flash_prefill_chunked_offset():
    """Chunked prefill: queries at positions P..P+Sq-1 over prefix+self KV."""
    B, P, Sq, Hkv, D = 1, 24, 18, 2, 128
    q, k, v = _flash_setup(B, Sq, P + Sq, 4, Hkv, D, seed=3)
    want = causal_attention(q, k, v, q_offset=P)
    got = flash_causal_attention_pallas(
        q, k, v, q_offset=P, interpret=True, block_q=16, block_k=16
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_flash_prefill_single_row():
    q, k, v = _flash_setup(1, 1, 1, 4, 2, 128, seed=5)
    want = causal_attention(q, k, v)
    got = flash_causal_attention_pallas(q, k, v, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_flash_prefix_kernel_matches_xla():
    """Bucketed-prefix flash kernel vs the XLA padded-prefix mask path:
    valid prefix rows attended, slack masked, self causal."""
    B, Sq, Hkv, D = 1, 18, 2, 128
    prefix_pad = 32  # 2 k-blocks at block_k=16
    for plen in [5, 16, 31, 32]:
        rng = np.random.default_rng(plen)
        q = jnp.asarray(rng.standard_normal((B, Sq, 4, D)), jnp.float32)
        k = jnp.asarray(
            rng.standard_normal((B, prefix_pad + Sq, Hkv, D)), jnp.float32
        )
        v = jnp.asarray(
            rng.standard_normal((B, prefix_pad + Sq, Hkv, D)), jnp.float32
        )
        pl_arr = jnp.asarray(plen, jnp.int32)
        want = causal_attention(
            q, k, v, prefix_pad=prefix_pad, prefix_len=pl_arr
        )
        from infinistore_tpu.ops import flash_prefix_attention_pallas

        got = flash_prefix_attention_pallas(
            q, k, v, prefix_pad=prefix_pad, prefix_len=pl_arr,
            interpret=True, block_q=16, block_k=16,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
            err_msg=f"plen={plen}",
        )


def test_flash_prefix_kernel_bf16():
    B, Sq, Hkv, D = 2, 16, 2, 128
    prefix_pad = 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Sq, 8, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, prefix_pad + Sq, Hkv, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, prefix_pad + Sq, Hkv, D)), jnp.bfloat16)
    pl_arr = jnp.asarray(9, jnp.int32)
    want = causal_attention(q, k, v, prefix_pad=prefix_pad, prefix_len=pl_arr)
    from infinistore_tpu.ops import flash_prefix_attention_pallas

    got = flash_prefix_attention_pallas(
        q, k, v, prefix_pad=prefix_pad, prefix_len=pl_arr,
        interpret=True, block_q=16, block_k=16,
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


# ---- on-chip Mosaic acceptance (TPU-gated; VERDICT r2 weak #2 / next #9) ----
#
# Everything above runs the kernels in interpret mode on the CPU mesh; these
# run them through the REAL Mosaic compile path whenever hardware is
# reachable, so the shipped on-TPU default path is exercised by the suite,
# not first compiled in production.  Run with:
#   ISTPU_TEST_TPU=1 python -m pytest tests/test_ops.py -k on_tpu
# (the env gate short-circuits BEFORE touching jax.devices(), so a wedged
# TPU tunnel cannot hang collection on CPU-only runs).


def _on_tpu() -> bool:
    import os

    if not os.environ.get("ISTPU_TEST_TPU"):
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — no backend at all
        return False


requires_tpu = pytest.mark.skipif(
    not _on_tpu(), reason="needs real TPU (set ISTPU_TEST_TPU=1)"
)


@requires_tpu
def test_paged_decode_kernel_mosaic_on_tpu():
    """interpret=False: Mosaic must accept the paged-decode kernel and its
    output must match the XLA path at serving shapes (8B head config)."""
    Hkv, D, T = 8, 128, 16
    q, cache, table, lens = _setup(
        4, 32, Hkv, D, T, 64, 8, dtype=jnp.bfloat16
    )
    want = paged_decode_attention_xla(q, cache, table, lens)
    got = paged_decode_attention_pallas(q, cache, table, lens)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2,
    )


@requires_tpu
def test_flash_prefill_mosaic_on_tpu():
    B, S, Hkv, D = 1, 512, 8, 128
    q, k, v = _flash_setup(B, S, S, 32, Hkv, D, dtype=jnp.bfloat16)
    want = causal_attention(q, k, v)
    got = flash_causal_attention_pallas(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2,
    )


@requires_tpu
def test_flash_prefix_kernel_mosaic_on_tpu():
    from infinistore_tpu.ops import flash_prefix_attention_pallas

    B, Sq, Hkv, D = 1, 128, 8, 128
    prefix_pad = 256
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Sq, 32, D)), jnp.bfloat16)
    k = jnp.asarray(
        rng.standard_normal((B, prefix_pad + Sq, Hkv, D)), jnp.bfloat16
    )
    v = jnp.asarray(
        rng.standard_normal((B, prefix_pad + Sq, Hkv, D)), jnp.bfloat16
    )
    pl_arr = jnp.asarray(200, jnp.int32)
    want = causal_attention(q, k, v, prefix_pad=prefix_pad, prefix_len=pl_arr)
    got = flash_prefix_attention_pallas(
        q, k, v, prefix_pad=prefix_pad, prefix_len=pl_arr
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2,
    )


def test_alllayers_decode_kernel_matches_per_layer():
    """The invocation-overhead instrument
    (paged_decode_attention_pallas_alllayers) must compute EXACTLY what L
    back-to-back single-layer kernel calls compute — it exists to vary
    only the invocation count (bench leg_invocation_overhead)."""
    from infinistore_tpu.ops.pallas_attention import (
        paged_decode_attention_pallas_alllayers,
    )

    L, Hkv, n_rep, D, T = 3, 2, 4, 128, 16
    B, max_pages, n_blocks = 2, 4, 16
    rng = np.random.default_rng(3)
    qs = jnp.asarray(rng.standard_normal((L, B, Hkv * n_rep, D)), jnp.float32)
    cache = jnp.asarray(
        rng.standard_normal((L, 2, Hkv, n_blocks, T, D)), jnp.float32
    )
    _, _, table, lens = _setup(
        B, Hkv * n_rep, Hkv, D, T, n_blocks, max_pages, seed=3
    )
    want = jnp.stack([
        paged_decode_attention_pallas(
            qs[l], cache[l], table, lens, interpret=True)
        for l in range(L)
    ])
    got = paged_decode_attention_pallas_alllayers(
        qs, cache, table, lens, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


@requires_tpu
def test_alllayers_decode_kernel_mosaic_on_tpu():
    """interpret=False: Mosaic must accept the all-layers instrument
    kernel (the invocation-overhead experiment's fused side) and match
    L back-to-back single-layer kernel calls at serving shapes."""
    from infinistore_tpu.ops.pallas_attention import (
        paged_decode_attention_pallas_alllayers,
    )

    L, Hkv, D, T = 4, 8, 128, 16
    rng = np.random.default_rng(11)
    qs = jnp.asarray(
        rng.standard_normal((L, 4, 32, D)), jnp.bfloat16)
    cache = jnp.asarray(
        rng.standard_normal((L, 2, Hkv, 64, T, D)), jnp.bfloat16)
    _, _, table, lens = _setup(4, 32, Hkv, D, T, 64, 8, seed=11,
                               dtype=jnp.bfloat16)
    want = jnp.stack([
        paged_decode_attention_pallas(qs[l], cache[l], table, lens)
        for l in range(L)
    ])
    got = paged_decode_attention_pallas_alllayers(qs, cache, table, lens)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2,
    )
