"""Speculative decoding: the output must be EXACTLY the target's greedy
decode — speculation may only change how many dispatches it takes (plus the
verify step's own correctness against the scan decode path)."""

import jax
import jax.numpy as jnp
import numpy as np

from infinistore_tpu.engine import InferenceEngine
from infinistore_tpu.engine.speculative import SpeculativeDecoder
from infinistore_tpu.kv import PagedCacheConfig
from infinistore_tpu.models import TINY, init_params, scaled

CFG = scaled(TINY, dtype=jnp.float32)
TARGET_PARAMS = init_params(CFG, jax.random.PRNGKey(7))
# the draft shares the vocab but is a different (worse) model — correctness
# must not depend on draft quality
DRAFT_CFG = scaled(TINY, dtype=jnp.float32, n_layers=1, dim=64, ffn_dim=128)
DRAFT_PARAMS = init_params(DRAFT_CFG, jax.random.PRNGKey(99))
T = 4


def make_engine(params, cfg):
    pc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        n_blocks=64, block_tokens=T, dtype=cfg.dtype,
    )
    return InferenceEngine(params, cfg, pc)


PROMPT = [11, 42, 7, 99, 5, 3, 17, 28, 64, 1, 2]


def test_verify_matches_decode_path():
    """One multi-token verify must produce the same logits trajectory as
    token-by-token decoding (and leave an equivalent cache behind)."""
    eng_a = make_engine(TARGET_PARAMS, CFG)
    st_a = eng_a.prefill(PROMPT)
    toks = eng_a.decode(st_a, 4)  # scan path

    eng_b = make_engine(TARGET_PARAMS, CFG)
    st_b = eng_b.prefill(PROMPT)
    assert int(jnp.argmax(st_b.last_logits)) == toks[0]
    # feed the scan path's own output through verify; the greedy choice
    # after consuming each token must reproduce the next token
    logits = eng_b.verify(st_b, toks[:3], len(st_b.tokens))
    choices = [int(c) for c in np.asarray(jnp.argmax(logits, axis=-1))]
    assert choices == toks[1:4]


def test_speculative_equals_greedy():
    want = make_engine(TARGET_PARAMS, CFG).generate(PROMPT, 24)

    spec = SpeculativeDecoder(
        make_engine(TARGET_PARAMS, CFG),
        make_engine(DRAFT_PARAMS, DRAFT_CFG),
        k=4,
    )
    got = spec.generate(PROMPT, 24)
    assert got == want
    assert spec.rounds >= 1


def test_fused_rounds_used_and_match_host_loop():
    """The greedy path compiles whole rounds into one dispatch per R rounds
    (_build_fused_rounds).  Pin that (a) the fused program actually engages
    for an eligible request — not a silent fallback to the host loop — and
    (b) its output is identical to the host round loop's."""
    from infinistore_tpu.engine.engine import _JIT_CACHE

    spec = SpeculativeDecoder(
        make_engine(TARGET_PARAMS, CFG),
        make_engine(DRAFT_PARAMS, DRAFT_CFG),
        k=4,
    )
    got_fused = spec.generate(PROMPT, 24)
    assert spec.rounds >= 1
    assert any(
        isinstance(key, tuple) and key and key[0] == "spec_fused"
        for key in _JIT_CACHE
    ), "fused-round program never compiled — fast path silently skipped"

    host = SpeculativeDecoder(
        make_engine(TARGET_PARAMS, CFG),
        make_engine(DRAFT_PARAMS, DRAFT_CFG),
        k=4,
    )
    host.fuse_rounds = False
    assert host.generate(PROMPT, 24) == got_fused


def test_batched_fused_rounds_match_per_row_greedy():
    """decode_batch: B rows of different lengths run the fused rounds in
    lockstep; every row's output must equal the target's own greedy
    decode of that prompt."""
    prompts = [PROMPT, PROMPT[:7], list(PROMPT) + [29, 31, 37]]
    wants = []
    ref = make_engine(TARGET_PARAMS, CFG)
    for p in prompts:
        wants.append(ref.generate(p, 18))

    spec = SpeculativeDecoder(
        make_engine(TARGET_PARAMS, CFG),
        make_engine(DRAFT_PARAMS, DRAFT_CFG),
        k=4,
    )
    st_ts, st_ds = zip(*[spec.prefill(p) for p in prompts])
    outs = spec.decode_batch(list(st_ts), list(st_ds), 18)
    assert outs == wants
    assert spec.rounds >= 3  # every row's rounds counted


def test_scheduler_spec_batch_matches_plain():
    """Scheduler(spec_batch=3): three concurrent greedy requests ride the
    batched fused rounds and must produce exactly the lockstep
    scheduler's outputs; acceptance counters advance."""
    sched = Scheduler(
        make_engine(TARGET_PARAMS, CFG),
        draft_engine=make_engine(DRAFT_PARAMS, DRAFT_CFG),
        spec_k=4, spec_batch=3,
    )
    prompts = [PROMPT, PROMPT[:8], list(PROMPT) + [41, 43]]
    rids = [sched.submit(p, max_new_tokens=16) for p in prompts]
    got = sched.run()

    plain = Scheduler(make_engine(TARGET_PARAMS, CFG))
    prids = [plain.submit(p, max_new_tokens=16) for p in prompts]
    want = plain.run()
    assert [got[r] for r in rids] == [want[r] for r in prids]
    assert sched.spec.rounds >= 1


def test_scheduler_spec_batch_ineligible_falls_back():
    """spec_batch > 1 with a decoder that can't fuse (fuse_rounds off)
    must fall back to lockstep decode, not crash the scheduler loop —
    decode_batch asserts its preconditions, so the gate must catch them."""
    sched = Scheduler(
        make_engine(TARGET_PARAMS, CFG),
        draft_engine=make_engine(DRAFT_PARAMS, DRAFT_CFG),
        spec_k=4, spec_batch=2,
    )
    sched.spec.fuse_rounds = False
    prompts = [PROMPT, PROMPT[:8]]
    rids = [sched.submit(p, max_new_tokens=8) for p in prompts]
    got = sched.run()

    plain = Scheduler(make_engine(TARGET_PARAMS, CFG))
    prids = [plain.submit(p, max_new_tokens=8) for p in prompts]
    want = plain.run()
    assert [got[r] for r in rids] == [want[r] for r in prids]


def test_speculative_self_draft_accepts_everything():
    """Draft == target: every proposal must be accepted (acceptance rate 1)
    and each round must emit k+1 tokens."""
    spec = SpeculativeDecoder(
        make_engine(TARGET_PARAMS, CFG),
        make_engine(TARGET_PARAMS, CFG),
        k=3,
    )
    want = make_engine(TARGET_PARAMS, CFG).generate(PROMPT, 12)
    got = spec.generate(PROMPT, 12)
    assert got == want
    assert spec.acceptance_rate == 1.0


def test_speculative_moe_family():
    """The verify contract generalizes: MoE target + MoE draft via
    verify_fn (and a missing verify_fn on a custom family raises clearly)."""
    import pytest

    from infinistore_tpu.models import (
        TINY_MOE,
        init_moe_params,
        moe_decode_forward,
        moe_prefill_forward,
        moe_verify_forward,
        scaled_moe,
    )

    mcfg = scaled_moe(TINY_MOE, dtype=jnp.float32)
    mparams = init_moe_params(mcfg, jax.random.PRNGKey(5))

    def moe_engine(with_verify=True):
        pc = PagedCacheConfig(
            n_layers=mcfg.n_layers, n_kv_heads=mcfg.n_kv_heads,
            head_dim=mcfg.head_dim, n_blocks=64, block_tokens=T,
            dtype=mcfg.dtype,
        )
        return InferenceEngine(
            mparams, mcfg, pc,
            prefill_fn=moe_prefill_forward,
            decode_fn=moe_decode_forward,
            verify_fn=moe_verify_forward if with_verify else None,
        )

    want = moe_engine().generate(PROMPT, 10)
    spec = SpeculativeDecoder(moe_engine(), moe_engine(), k=3)
    assert spec.generate(PROMPT, 10) == want
    assert spec.acceptance_rate == 1.0  # self-draft

    bad = moe_engine(with_verify=False)
    st = bad.prefill(PROMPT)
    with pytest.raises(ValueError, match="verify_fn"):
        bad.verify(st, [1, 2], len(st.tokens))


def test_stochastic_self_draft_accepts_everything():
    """Draft == target: p == q, so min(1, p/q) == 1 and every proposal is
    accepted (up to f32 noise between the scan and verify forwards)."""
    spec = SpeculativeDecoder(
        make_engine(TARGET_PARAMS, CFG),
        make_engine(TARGET_PARAMS, CFG),
        k=3,
    )
    out = spec.generate(PROMPT, 12, sample="categorical", temperature=0.9,
                        top_p=0.8)
    assert len(out) == 12
    assert all(0 <= t < CFG.vocab_size for t in out)
    assert spec.acceptance_rate >= 0.9


def test_stochastic_speculative_matches_target_distribution():
    """The rejection-sampling guarantee: each emitted token is an exact
    draw from the target's post-truncation distribution regardless of the
    draft.  Chi-squared over the top-k support of the first emitted token,
    against the target's own sampling_probs; fixed seeds keep the test
    deterministic."""
    target = make_engine(TARGET_PARAMS, CFG)
    draft = make_engine(DRAFT_PARAMS, DRAFT_CFG)
    spec = SpeculativeDecoder(target, draft, k=3)
    st_t, st_d = spec.prefill(PROMPT)
    base_t, base_d = list(st_t.tokens), list(st_d.tokens)
    logits_t, logits_d = st_t.last_logits, st_d.last_logits

    # pure temperature sampling: full-support overlap between p and q, so
    # both the accept path AND the reject/residual path run (truncation
    # would make the random draft's and target's top-k supports disjoint
    # and force rejection every round)
    TEMP = 1.0
    p = np.asarray(
        target.sampling_probs(logits_t[None], temperature=TEMP),
        dtype=np.float64,
    )[0]

    N = 240  # deterministic (fixed seeds); smallest bin still ~5 expected
    counts: dict = {}
    for i in range(N):
        st_t.tokens, st_t.last_logits = list(base_t), logits_t
        st_d.tokens, st_d.last_logits = list(base_d), logits_d
        tok = spec.decode(
            st_t, st_d, 1, sample="categorical", temperature=TEMP,
            rng=jax.random.PRNGKey(1000 + i),
        )[0]
        counts[tok] = counts.get(tok, 0) + 1
    # both the accept and the reject/residual paths actually ran
    assert 0.0 < spec.acceptance_rate < 1.0, spec.acceptance_rate
    # chi-squared over the 7 most likely tokens + everything-else bucket
    # (full-vocab bins would leave expected counts < 5)
    top = np.argsort(-p)[:7]
    exp = [N * p[t] for t in top] + [N * (1.0 - p[top].sum())]
    obs = [counts.get(int(t), 0) for t in top]
    obs.append(N - sum(obs))
    chi2 = sum((o - e) ** 2 / e for o, e in zip(obs, exp))
    # df=7, p=0.001 critical value 24.32; fixed seeds => deterministic
    assert chi2 < 24.32, (chi2, counts)


def test_speculative_continues_after_decode():
    """The target state stays usable for plain decode after speculation."""
    spec = SpeculativeDecoder(
        make_engine(TARGET_PARAMS, CFG),
        make_engine(DRAFT_PARAMS, DRAFT_CFG),
        k=2,
    )
    st_t, st_d = spec.prefill(PROMPT)
    first = spec.decode(st_t, st_d, 7)
    more = spec.target.decode(st_t, 5)
    want = make_engine(TARGET_PARAMS, CFG).generate(PROMPT, 12)
    assert first + more == want


def test_speculative_windowed_family():
    """Sliding-window target: the multi-token verify mask must agree with
    the scan decode mask, so speculation still reproduces greedy exactly."""
    # window 8, PRNGKey(21): the SAME (cfg, params) as test_engine's SWA
    # tests and this module's reclaim test — one set of compiled programs
    # serves all of them via the process-wide jit cache
    wcfg = scaled(TINY, dtype=jnp.float32, sliding_window=8)
    wparams = init_params(wcfg, jax.random.PRNGKey(21))
    want = make_engine(wparams, wcfg).generate(PROMPT, 16)
    spec = SpeculativeDecoder(
        make_engine(wparams, wcfg),
        make_engine(DRAFT_PARAMS, DRAFT_CFG),
        k=4,
    )
    assert spec.generate(PROMPT, 16) == want


# ---- scheduler integration: speculation as the batch=1 fast path ----
# (VERDICT r3 next #2: speculation must be SERVABLE, not a library class)

from infinistore_tpu.engine import Scheduler  # noqa: E402


def make_spec_scheduler(**kw):
    return Scheduler(
        make_engine(TARGET_PARAMS, CFG),
        draft_engine=make_engine(DRAFT_PARAMS, DRAFT_CFG),
        spec_k=4, **kw,
    )


def test_scheduler_speculative_equals_plain_greedy():
    """A lone greedy request served through the speculative fast path must
    produce exactly what the plain scheduler produces."""
    plain = Scheduler(make_engine(TARGET_PARAMS, CFG))
    rid = plain.submit(PROMPT, max_new_tokens=20)
    want = plain.run()[rid]

    sched = make_spec_scheduler()
    rid = sched.submit(PROMPT, max_new_tokens=20)
    got = sched.run()[rid]
    assert got == want
    assert sched.spec.rounds >= 1  # the fast path actually ran
    assert sched.spec_metrics["proposed"] > 0


def test_scheduler_speculative_draft_pages_released():
    """Draft pages must return to the draft allocator at retirement —
    serving many sequential requests through speculation must not leak."""
    sched = make_spec_scheduler()
    free0 = sched.draft.free_pages
    for _ in range(3):
        rid = sched.submit(PROMPT, max_new_tokens=8)
        sched.run()
    assert sched.draft.free_pages == free0


def test_scheduler_speculation_disabled_for_batches():
    """Two concurrent requests take the lockstep path (speculation is the
    batch=1 fast path) and still match the plain scheduler's outputs."""
    plain = Scheduler(make_engine(TARGET_PARAMS, CFG))
    ra = plain.submit(PROMPT, max_new_tokens=12)
    rb = plain.submit(PROMPT[:5], max_new_tokens=12)
    want = plain.run()

    sched = make_spec_scheduler()
    ga = sched.submit(PROMPT, max_new_tokens=12)
    gb = sched.submit(PROMPT[:5], max_new_tokens=12)
    got = sched.run()
    assert got[ga] == want[ra]
    assert got[gb] == want[rb]
    # batch admission wave of 2: the fast path never engaged
    assert sched.spec.rounds == 0


def test_scheduler_speculation_reengages_after_batch_drains():
    """Mixed timeline: a lone request speculates; a second arrives (fast
    path off, draft dropped); after it finishes the survivor re-enters the
    fast path with a fresh draft prefill.  Output must equal plain greedy
    end to end."""
    plain = Scheduler(make_engine(TARGET_PARAMS, CFG))
    rid = plain.submit(PROMPT, max_new_tokens=30)
    want_long = plain.run()[rid]
    plain2 = Scheduler(make_engine(TARGET_PARAMS, CFG))
    rid2 = plain2.submit(PROMPT[:4], max_new_tokens=6)
    # the short request joins mid-flight in the spec scheduler, so its
    # reference output must be computed against the same join dynamics —
    # only the LONG request's output is asserted exactly; the short one is
    # asserted against its own isolated greedy decode (greedy decode is
    # batch-independent in this engine: lockstep rows are masked per-row)
    want_short = plain2.run()[rid2]

    sched = make_spec_scheduler()
    ga = sched.submit(PROMPT, max_new_tokens=30)
    results = {}
    # let the lone request speculate a few chunks
    for _ in range(2):
        for r in sched.step():
            results[r.req_id] = r.output
    rounds_before = sched.spec.rounds
    assert rounds_before >= 1
    gb = sched.submit(PROMPT[:4], max_new_tokens=6)
    while sched.has_work:
        for r in sched.step():
            results[r.req_id] = r.output
    assert results[ga] == want_long
    assert results[gb] == want_short
    # speculation re-engaged after the short request retired
    assert sched.spec.rounds > rounds_before


def test_scheduler_spec_draft_pool_dry_falls_back_correctly():
    """A draft pool that dries up MID-ROUND must not corrupt the served
    output: spec.decode restores decode-readiness (tail re-verify) before
    the scheduler falls back to the lockstep path, and the request stays
    on that path instead of thrashing draft prefills (regression for the
    stale-last_logits / unwritten-KV fallback bug)."""
    draft_pc = PagedCacheConfig(
        n_layers=DRAFT_CFG.n_layers, n_kv_heads=DRAFT_CFG.n_kv_heads,
        head_dim=DRAFT_CFG.head_dim, n_blocks=4, block_tokens=T,
        dtype=DRAFT_CFG.dtype,
    )
    sched = Scheduler(
        make_engine(TARGET_PARAMS, CFG),
        draft_engine=InferenceEngine(DRAFT_PARAMS, DRAFT_CFG, draft_pc),
        spec_k=4,
    )
    rid = sched.submit(PROMPT, max_new_tokens=24)
    got = sched.run()[rid]

    plain = Scheduler(make_engine(TARGET_PARAMS, CFG))
    rid2 = plain.submit(PROMPT, max_new_tokens=24)
    want = plain.run()[rid2]
    assert got == want
    # the tight pool actually forced the fallback (otherwise this test
    # isn't exercising the failure path)
    assert sched.spec.rounds >= 1
    assert sched.draft.free_pages == 4  # draft state dropped, pages home


def test_scheduler_spec_windowed_target_reclaims_pages():
    """Fully-windowed target on the speculative fast path: verify() never
    reclaims, so the fast path must reclaim at entry — a pool too small
    for the un-reclaimed generation still completes WITHOUT tripping the
    mid-round MemoryError that would permanently disable speculation."""
    from infinistore_tpu.models import init_params, scaled

    wcfg = scaled(CFG, sliding_window=8)
    wparams = init_params(wcfg, jax.random.PRNGKey(21))

    def weng():
        # the STANDARD test pool shape (64 x T): a bespoke small pool
        # would compile a whole second windowed program universe — pool
        # pressure is created below by hoarding pages instead
        return make_engine(wparams, wcfg)

    plain = Scheduler(weng())
    rid = plain.submit(PROMPT, max_new_tokens=44)
    want = plain.run()[rid]

    # 11 + 44 tokens -> 14 pages un-reclaimed; hoard pages until only 12
    # remain so reclamation is forced WITHOUT a bespoke cache shape
    pressured = weng()
    hoard = pressured.pages.acquire(64 - 12)
    assert pressured.free_pages == 12
    sched = Scheduler(pressured, draft_engine=make_engine(
        DRAFT_PARAMS, DRAFT_CFG), spec_k=4)
    rid = sched.submit(PROMPT, max_new_tokens=44)
    results = {}
    reqs = []
    while sched.has_work:
        for r in sched.step():
            results[r.req_id] = r.output
            reqs.append(r)
    assert results[rid] == want
    assert reqs and not reqs[0]._spec_off  # speculation survived throughout
    assert sched.spec.rounds >= 5


def test_scheduler_fault_reset_releases_everything():
    """fault_reset: every page (target and draft) returns to the pools,
    queues drain, and dropped requests come back marked done."""
    sched = make_spec_scheduler()
    t_free0 = sched.engine.free_pages
    d_free0 = sched.draft.free_pages
    a = sched.submit(PROMPT, max_new_tokens=500)
    b = sched.submit(PROMPT[:6], max_new_tokens=500)
    for _ in range(2):
        sched.step()
    dropped = sched.fault_reset()
    assert {r.req_id for r in dropped} == {a, b}
    assert all(r.done and r.state is None and r._draft_state is None
               for r in dropped)
    assert not sched.has_work
    assert sched.engine.free_pages == t_free0
    assert sched.draft.free_pages == d_free0
    # the scheduler stays usable after the reset
    c = sched.submit(PROMPT, max_new_tokens=5)
    assert len(sched.run()[c]) == 5


def test_stale_shorter_draft_with_repeated_tail_rejected():
    """A draft whose tokens are SHORTER than the target's but whose last
    k+2 values happen to match (repeated-token tail) must not pass the
    fused-path sync gate: decode() has to fall back to the host loop
    (which re-syncs), and decode_batch() has to refuse outright.
    Regression for the advisor r4 medium finding — the value-only gate
    let a stale draft undersize its block table."""
    k = 4
    tail = [9] * (k + 2)
    prompt = [11, 42, 7] + tail
    spec = SpeculativeDecoder(
        make_engine(TARGET_PARAMS, CFG),
        make_engine(DRAFT_PARAMS, DRAFT_CFG),
        k=k,
    )
    st_t, st_d = spec.prefill(prompt)
    # simulate a lockstep interlude: target advanced, draft did not —
    # but the emitted tokens repeat the tail value, so the last k+2
    # VALUES still compare equal
    st_t.tokens = st_t.tokens + [9, 9, 9]
    assert st_t.tokens[-(k + 2):] == st_d.tokens[-(k + 2):]
    assert len(st_t.tokens) != len(st_d.tokens)

    import pytest
    with pytest.raises(AssertionError, match="out of sync"):
        spec.decode_batch([st_t], [st_d], 4)

    # decode()'s gate must ALSO reject the stale draft: with the same
    # value-equal/length-unequal states it has to route to the host
    # round loop (which resyncs the draft), never the fused path
    class _HostLoop(Exception):
        pass

    def _sentinel(*a, **k):
        raise _HostLoop

    spec._rounds = _sentinel
    with pytest.raises(_HostLoop):
        spec.decode(st_t, st_d, 4)


def test_ngram_speculator_matches_greedy():
    """Model-free n-gram speculation (engine/ngram.py): batched rows of
    different lengths and repetitiveness must all emit EXACTLY the
    target's greedy decode — acceptance only changes the dispatch
    count.  (vLLM's [ngram] speculator / prompt-lookup decoding is the
    reference-stack counterpart.)"""
    from infinistore_tpu.engine.ngram import NgramSpeculator

    prompts = [PROMPT, PROMPT[:7], [5, 6, 7, 8] * 6]
    ref = make_engine(TARGET_PARAMS, CFG)
    wants = [ref.generate(p, 30) for p in prompts]

    # k=4, g=2 everywhere in this file: every distinct (k, g, B, L, R)
    # tuple compiles its own fused program, so the correctness tests
    # share ONE universe (the scheduler test below uses the same pair)
    spec = NgramSpeculator(make_engine(TARGET_PARAMS, CFG), k=4, g=2)
    sts = [spec.prefill(p) for p in prompts]
    outs = spec.decode_batch(sts, 30)
    assert outs == wants
    assert spec.rounds >= 3

    # single-row convenience path (B=1 specializes separately; 8 tokens
    # keeps it inside the R=2 bucket — R=8 coverage comes from the
    # batched run above)
    s2 = NgramSpeculator(make_engine(TARGET_PARAMS, CFG), k=4, g=2)
    assert s2.generate(prompts[0], 8) == wants[0][:8]


def test_ngram_speculator_short_prompt_falls_back():
    """Prompts shorter than g+1 can't seed a match window: decode() must
    fall back to plain target decode, still exact."""
    from infinistore_tpu.engine.ngram import NgramSpeculator

    ref = make_engine(TARGET_PARAMS, CFG)
    want = ref.generate(PROMPT[:2], 10)
    spec = NgramSpeculator(make_engine(TARGET_PARAMS, CFG), k=4, g=3)
    st = spec.prefill(PROMPT[:2])
    assert not spec.eligible(st)
    assert spec.decode(st, 10) == want


def test_scheduler_ngram_spec_matches_plain():
    """Scheduler(ngram_spec=True): greedy requests ride the model-free
    fused rounds and must produce exactly the plain scheduler's outputs;
    acceptance counters advance; a sampled request makes the step fall
    back to lockstep decode (identical streams — the ngram path never
    consumes scheduler rng)."""
    sched = Scheduler(
        make_engine(TARGET_PARAMS, CFG),
        ngram_spec=True, spec_k=4, spec_g=2, spec_batch=3,
    )
    prompts = [PROMPT, PROMPT[:8], [5, 6, 7, 8] * 5]
    rids = [sched.submit(p, max_new_tokens=12) for p in prompts]
    got = sched.run()

    plain = Scheduler(make_engine(TARGET_PARAMS, CFG))
    prids = [plain.submit(p, max_new_tokens=12) for p in prompts]
    want = plain.run()
    assert [got[r] for r in rids] == [want[r] for r in prids]
    assert sched.spec.rounds >= 1
    assert sched.spec_metrics["proposed"] > 0

    # sampled request: ngram path refuses (delta proposals can't do
    # rejection sampling), lockstep fallback still matches plain
    s2 = Scheduler(make_engine(TARGET_PARAMS, CFG),
                   ngram_spec=True, spec_k=4, spec_g=2)
    r2 = s2.submit(PROMPT, max_new_tokens=8, sample="categorical",
                   temperature=1.5, seed=3)
    p2 = Scheduler(make_engine(TARGET_PARAMS, CFG))
    r3 = p2.submit(PROMPT, max_new_tokens=8, sample="categorical",
                   temperature=1.5, seed=3)
    assert s2.run()[r2] == p2.run()[r3]
    assert s2.spec.rounds == 0  # never engaged


def test_distilled_draft_learns_target_outputs():
    """engine/distill.py end to end: corpus from the target's own greedy
    trajectories, a small draft distilled on it (f32 master weights),
    and the measured speculation acceptance on a corpus prompt goes to
    ~1 — draft proposals then carry whole rounds (tokens/round ≈ k+1),
    while output remains EXACTLY the target's greedy decode."""
    from infinistore_tpu.engine.distill import (
        acceptance_probe,
        distill,
        generate_corpus,
    )

    tparams = init_params(CFG, jax.random.PRNGKey(7))
    corpus = generate_corpus(
        make_engine(tparams, CFG), n_seqs=8, prompt_len=8, gen_len=40,
        batch=4,  # 4 rows x 12 pages fits the standard 64-page pool
    )
    dcfg = scaled(TINY, dtype=jnp.float32, n_layers=1, dim=64, ffn_dim=128)
    dparams, losses = distill(dcfg, corpus, steps=700, lr=2e-2, batch=8)
    assert losses[-1] < 1.0 < losses[0]  # it actually trained

    prompt = [int(t) for t in corpus[0][:8]]
    acc, per_round = acceptance_probe(
        make_engine(tparams, CFG), make_engine(dparams, dcfg),
        [prompt], gen_len=32, k=4,
    )
    assert acc > 0.8, acc
    assert per_round > 4.0, per_round

    # exactness is acceptance-independent: the distilled-draft output IS
    # the target's greedy decode
    want = make_engine(tparams, CFG).generate(prompt, 16)
    spec = SpeculativeDecoder(
        make_engine(tparams, CFG), make_engine(dparams, dcfg), k=4)
    assert spec.generate(prompt, 16) == want


# ---- round 11: single-sync restructure (adaptive R, device reconcile) ----


def test_adaptive_controller_bucket_choices_follow_acceptance():
    """Injected acceptance sequences → bucket choices: a fresh
    controller is optimistic (covers the chunk with the smallest
    sufficient bucket); a weak draft walks the EWMA down and the
    suggestion up toward the largest bucket; recovery walks it back."""
    from infinistore_tpu.engine.speculative import AdaptiveRController

    ctl = AdaptiveRController(k=4, buckets=(1, 2, 8))
    assert ctl.rate == 5.0  # optimistic start: full acceptance
    # 32-token chunk at rate 5 needs ~7 rounds -> bucket 8
    assert ctl.suggest(32) == 8
    # a short tail the EWMA covers in one round -> smallest bucket
    assert ctl.suggest(4) == 1
    # feed a weak draft: 1 token/round for a while
    for _ in range(20):
        ctl.update(1, 1)
    assert ctl.rate < 1.5
    # now even a short remaining budget needs the big program
    assert ctl.suggest(8) == 8
    # recovery: full rounds again
    for _ in range(20):
        ctl.update(5, 1)
    assert ctl.rate > 4.5
    # (7 not 8: at remaining=8 the down-switch margin 2*rate >= 8*1.25
    # sits exactly at the EWMA's asymptote — by-design hysteresis)
    assert ctl.suggest(7) == 2


def test_adaptive_controller_bounded_set_and_hysteresis():
    """Suggestions never leave the configured bucket set, and the
    down-switch margin keeps an EWMA wobbling around a bucket boundary
    from flapping between two compiled programs."""
    from infinistore_tpu.engine.speculative import AdaptiveRController

    ctl = AdaptiveRController(k=4, buckets=(2, 4, 8), hysteresis=0.25)
    seen = set()
    accept = [5, 1, 3, 5, 5, 1, 1, 4, 2, 5] * 4
    for a in accept:
        ctl.update(a, 1)
        seen.add(ctl.suggest(16))
    assert seen <= {2, 4, 8}

    # hysteresis: remaining=8, boundary between bucket 2 (needs rate 4)
    # and bucket 4.  At rate exactly 4.0 a DOWN-switch from 4 needs
    # 2 * 4.0 >= 8 * 1.25 — not met, so the controller stays at 4; a
    # margin-free controller would flip to 2 and back as the EWMA
    # wobbles across 4.0
    ctl2 = AdaptiveRController(k=4, buckets=(2, 4, 8), hysteresis=0.25)
    ctl2.rate, ctl2._bucket = 4.0, 4
    assert ctl2.suggest(8) == 4
    ctl2.rate = 4.4   # still inside the margin band (needs >= 5.0)
    assert ctl2.suggest(8) == 4
    ctl2.rate = 5.0   # clears the band: now the smaller program is safe
    assert ctl2.suggest(8) == 2
    # ...and staying down needs no margin even if the rate dips a bit
    ctl2.rate = 4.2
    assert ctl2.suggest(8) == 2


def test_r_bucket_env_parsing_is_bounded():
    """ISTPU_SPEC_R_BUCKETS parsing: sorted/deduped, clamped to at most
    4 values in [1, 32]; garbage falls back to the default — every
    bucket is a whole compiled program, so the set must stay bounded."""
    from infinistore_tpu.engine.speculative import _parse_r_buckets

    assert _parse_r_buckets(None) == (1, 2, 8)
    assert _parse_r_buckets("") == (1, 2, 8)
    assert _parse_r_buckets("8,2,1,2") == (1, 2, 8)
    assert _parse_r_buckets("4") == (4,)
    assert _parse_r_buckets("1,2,4,8,16,32") == (1, 2, 4, 8)  # clamped
    assert _parse_r_buckets("0,33,7") == (7,)  # out-of-range dropped
    assert _parse_r_buckets("nonsense") == (1, 2, 8)
    assert _parse_r_buckets("-3,0") == (1, 2, 8)


def test_stochastic_fused_tokens_invariant_across_r_buckets(monkeypatch):
    """The per-request-seed contract under changing R: stochastic draws
    fold the base key with the token's absolute position (draft) or the
    round's accepted length (accept/resample), so a fixed rng must
    reproduce the SAME tokens whatever the bucket set groups rounds
    into — across plain AND filter variants, and across call
    boundaries."""
    outs = {}
    for buckets in ("8", "1", "2,4"):
        monkeypatch.setenv("ISTPU_SPEC_R_BUCKETS", buckets)
        for kw in (
            {"temperature": 0.9},
            {"temperature": 0.9, "top_k": 12, "top_p": 0.85},
        ):
            spec = SpeculativeDecoder(
                make_engine(TARGET_PARAMS, CFG),
                make_engine(DRAFT_PARAMS, DRAFT_CFG), k=4,
            )
            st_t, st_d = spec.prefill(PROMPT)
            toks = spec.decode(
                st_t, st_d, 17, sample="categorical",
                rng=jax.random.PRNGKey(5), **kw,
            )
            key = tuple(sorted(kw.items()))
            outs.setdefault(key, []).append(toks)
    for key, runs in outs.items():
        assert all(r == runs[0] for r in runs), (key, runs)
    # chunk-boundary invariance: one 16-token call == two 8-token calls
    # under the same base rng (draws fold by absolute position/length)
    monkeypatch.setenv("ISTPU_SPEC_R_BUCKETS", "2,8")

    def run(chunks):
        spec = SpeculativeDecoder(
            make_engine(TARGET_PARAMS, CFG),
            make_engine(DRAFT_PARAMS, DRAFT_CFG), k=4,
        )
        st_t, st_d = spec.prefill(PROMPT)
        toks = []
        for c in chunks:
            toks += spec.decode(st_t, st_d, c, sample="categorical",
                                temperature=0.9,
                                rng=jax.random.PRNGKey(11))
        return toks

    assert run([16]) == run([8, 8])


def test_adaptive_controller_carried_per_request_and_forgotten():
    """The controller is carried per TARGET seq id across scheduler
    steps (acceptance learned on one chunk sizes the next) and dropped
    at retirement — a retired id's state must not leak."""
    sched = make_spec_scheduler()
    # 70 tokens = at least three 32-token chunks, so the controller
    # must survive across steps before retirement drops it
    rid = sched.submit(PROMPT, max_new_tokens=70)
    sched.step()
    assert sched.spec.adaptive
    assert len(sched.spec._ctls) == 1
    (ctl,) = sched.spec._ctls.items()
    seq_id, c0 = ctl
    rate_after_step1 = c0.rate
    assert rate_after_step1 < 5.0  # the weak draft moved the EWMA
    sched.step()
    assert sched.spec._ctls.get(seq_id) is c0, "controller not carried"
    sched.run()
    assert sched.spec._ctls == {}, "controller leaked past retirement"


def test_fused_batch_single_dispatch_at_full_acceptance():
    """Self-draft (acceptance 1) + adaptive R: a whole chunk must cost
    exactly ONE fused dispatch and ONE blocking sync, with ZERO host
    reconcile dispatches (verify/draft) — the structural core of the
    single-sync restructure, asserted from the step profiler record."""
    from infinistore_tpu.engine import stepprof as _sp
    from infinistore_tpu.engine.stepprof import StepProfiler

    spec = SpeculativeDecoder(
        make_engine(TARGET_PARAMS, CFG),
        make_engine(TARGET_PARAMS, CFG), k=3,
    )
    st_t, st_d = spec.prefill(PROMPT)
    spec.decode(st_t, st_d, 24)  # warm: compile the bucket programs
    st_t2, st_d2 = spec.prefill(list(PROMPT) + [29, 31])
    prof = StepProfiler(sample=1)
    with prof.step(kind_hint="spec") as rec:
        out = spec.decode(st_t2, st_d2, 24)
    assert len(out) == 24
    assert rec["dispatches"] == {"spec_round": 1}, rec["dispatches"]
    assert rec["syncs"] == {"spec_tokens": 1}, rec["syncs"]
