"""Operator tooling around the observability plane: the ``istpu-top``
console (pure rendering + live `--once` integration), the stable
``--json-out`` benchmark schema, trace-id-stamped log records, and the
metrics↔docs drift lint."""

import io
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# console rendering (no sockets)
# ---------------------------------------------------------------------------


def _metrics_text():
    from infinistore_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.gauge("istpu_store_pool_usage", "").set(0.42)
    reg.gauge("istpu_store_fragmentation", "").set(0.1)
    reg.gauge("istpu_store_kvmap_len", "").set(12)
    reg.counter("istpu_store_evicted_total", "").inc(3)
    reg.counter("istpu_serve_requests_total", "").inc(7)
    reg.counter("istpu_serve_completed_total", "").inc(6)
    reg.counter("istpu_serve_tokens_total", "").inc(90)
    reg.gauge("istpu_serve_free_kv_pages", "").set(55)
    reg.gauge("istpu_store_circuit_state", "", labelnames=("name",)
              ).labels("store").set(1)
    reg.counter("istpu_store_scrub_pages_total", "").inc(120)
    reg.counter("istpu_store_scrub_corrupt_total", "").inc(2)
    reg.counter("istpu_integrity_failures_total", "",
                labelnames=("cause",)).labels("checksum").inc(3)
    c = reg.counter("istpu_engine_prefix_tokens_total", "",
                    labelnames=("source",))
    c.labels("local").inc(8)
    c.labels("store").inc(8)
    c.labels("computed").inc(16)
    h = reg.histogram("istpu_serve_prefill_seconds", "")
    h.observe(0.1)
    return reg.to_prometheus_text()


def test_console_renders_synthetic_snapshot():
    from infinistore_tpu.top import Console, Snapshot
    from infinistore_tpu.utils.metrics import parse_prometheus_text

    cache = {
        "entries": 12, "hits": 30, "misses": 10, "hit_ratio": 0.75,
        "evicted": 3, "dead_on_arrival": 2, "mean_reuse_s": 1.5,
        "hot": [{"key": "k0", "hits": 9, "age_s": 0.5, "size": 1,
                 "since_commit_s": 2.0}],
        "cold": [{"key": "k9", "hits": 0, "age_s": 90.0, "size": 1,
                  "since_commit_s": 90.0}],
        "age_bands": {"<1s": {"entries": 3, "bytes": 3},
                      ">=10m": {"entries": 9, "bytes": 9}},
    }

    integrity = {
        "level": "scrub", "alg": "sum64", "epoch": 17858693167521,
        "unverified": 0, "scrub_pages": 120, "scrub_corrupt": 2,
        "quarantined": 2, "scrub_rate": 256.0,
    }

    def snap(extra_prefill=0.0):
        text = _metrics_text()
        return Snapshot(
            serve_metrics=parse_prometheus_text(text),
            store_metrics=parse_prometheus_text(text),
            cache=cache,
            serve_health={"status": "ok"},
            store_health={"status": "degraded"},
            integrity=integrity,
        )

    console = Console()
    console.frame(snap())        # first frame primes the rate trackers
    out = console.frame(snap())  # second frame has deltas
    assert "serve:ok" in out and "store:degraded" in out
    assert "circuit:OPEN" in out
    # the integrity row: level, epoch tail, scrub/corrupt/quarantine
    # counts fed from the new families, client verify failures
    assert "integrity scrub" in out
    assert "858693167521" in out           # epoch (last-12-digit tail)
    assert "scrubbed      120 pg" in out
    assert "corrupt    2" in out and "quarantined    2" in out
    assert "verify-fails 3" in out
    assert "pool occupancy" in out and "42.0%" in out
    assert "hit ratio" in out and "75.0%" in out
    assert "dead-on-arrival" in out and "2" in out
    # provenance split: 8/8/16 of 32 tokens
    assert "local  25.0%" in out.replace("local ", "local  ") or \
        "local" in out
    assert "hot keys" in out and "k0" in out and "k9" in out
    assert "occupancy by age" in out
    # an empty snapshot must not crash (unreachable stack)
    from infinistore_tpu.top import Snapshot as S
    assert Console().frame(S())


def test_console_renders_fleet_view():
    """The disaggregated-fleet section (router /debug/fleet): one row
    per worker with role/state/circuit/inflight, and a per-frame
    adoption-hit delta from each worker's store-loaded prompt tokens —
    pure Console.frame in the snapshot, per the established pattern."""
    from infinistore_tpu.top import Console, Snapshot

    def fleet(store_tok, victim_circuit="closed", victim_status="ok"):
        def worker(role, ep, circuit="closed", status="ok", tok=0.0,
                   shedding=False):
            return {
                "endpoint": ep, "role": role, "reachable": True,
                "status": status, "circuit": circuit, "inflight": 2,
                "shedding": shedding, "requests_total": 40,
                "completed_total": 38, "free_kv_pages": 200,
                "prefix_tokens": {"local": 8.0, "store": tok,
                                  "computed": 64.0},
            }

        return {
            "enabled": True, "role": "router",
            "workers": [
                worker("prefill", "10.0.0.1:8001",
                       circuit=victim_circuit, status=victim_status),
                worker("decode", "10.0.0.3:8003", tok=store_tok,
                       shedding=True),
            ],
            "rollup": {
                "prefill": {"workers": 1,
                            "ok": 1 if victim_status == "ok" else 0,
                            "degraded": 0, "unreachable": 0,
                            "circuit_open":
                                1 if victim_circuit == "open" else 0},
                "decode": {"workers": 1, "ok": 1, "degraded": 0,
                           "unreachable": 0, "circuit_open": 0},
            },
            "handoff": {"count": 12, "p50_ms": 14.2, "p99_ms": 90.5},
            "adoption": {"store_tokens": store_tok, "local_tokens": 8.0},
            "requests": {"2xx": 40, "4xx": 1, "5xx": 0, "error": 0},
        }

    console = Console()
    first = console.frame(Snapshot(fleet=fleet(96.0)))
    assert "fleet" in first and "prefill 1/1 ok" in first
    assert "handoff p50/p99 14.2/90.5 ms" in first
    assert "10.0.0.1:8001" in first and "10.0.0.3:8003" in first
    # first frame has no delta yet
    assert "Δadopt-tok/frame" in first
    # second frame: +128 adoption tokens on the decode worker, the
    # victim's circuit now OPEN and its row says so
    out = console.frame(Snapshot(
        fleet=fleet(224.0, victim_circuit="open",
                    victim_status="unreachable")))
    assert "+128" in out
    assert "OPEN" in out and "unreachabl" in out
    assert "ok+shed" in out  # shedding decode worker flagged in-state
    assert "prefill 0/1 ok" in out
    # a fleet-less snapshot renders no fleet section
    assert "fleet" not in Console().frame(Snapshot())
    # pre-replication payloads (no "router" block) render exactly as
    # before: no replica/resume row appears
    assert "replicas" not in first and "resumes" not in first


def test_console_renders_router_replica_and_resume_rows():
    """Replicated-router payloads grow a `router` block in /debug/fleet
    (replicas, stream splice ledger); the fleet view renders it as one
    row with a per-frame resume delta.  Old payloads (previous test)
    must render unchanged — the row is strictly additive."""
    from infinistore_tpu.top import Console, Snapshot

    def fleet(resumes_ok):
        return {
            "enabled": True, "role": "router",
            "workers": [{
                "endpoint": "10.0.0.3:8003", "role": "decode",
                "reachable": True, "status": "ok", "circuit": "closed",
                "inflight": 1, "requests_total": 9,
                "prefix_tokens": {"local": 0.0, "store": 0.0},
            }],
            "rollup": {"decode": {"workers": 1, "ok": 1, "degraded": 0,
                                  "unreachable": 0, "circuit_open": 0}},
            "handoff": {"count": 0, "p50_ms": None, "p99_ms": None},
            "requests": {"2xx": 9, "4xx": 0, "5xx": 0, "error": 0},
            "router": {
                "replicas": 3, "peers": ["http://127.0.0.1:9001",
                                         "http://127.0.0.1:9002"],
                "stream": {"aborts": 1.0,
                           "resumes": {"ok": resumes_ok, "failed": 1.0}},
            },
        }

    console = Console()
    first = console.frame(Snapshot(fleet=fleet(2.0)))
    assert "router   replicas 3" in first
    assert "resumes ok 2 failed 1" in first and "aborts 1" in first
    # second frame: two more splices landed — the delta names them
    out = console.frame(Snapshot(fleet=fleet(4.0)))
    assert "resumes ok 4" in out and "+2" in out


def test_console_renders_engine_view():
    """The engine-attribution section (serving /debug/engine): tokens
    and steps per frame, retraces, host-stall share, mem watermark bar."""
    from infinistore_tpu.top import Console, Snapshot

    def engine(tokens, steps, retr):
        return {
            "enabled": True, "sample": 16, "ring": 256,
            "summary": {
                "steps": steps, "tokens": tokens,
                "by_kind": {"prefill": 2, "decode": steps - 2},
                "dispatches": {"decode": steps}, "dispatch_total": steps,
                "host_stall_frac": 0.42, "retraces_total": retr,
                "retraces_per_100_steps": 2.5, "compiles": 7,
                "sampled_steps": 2, "host_stall_s": 0.5, "wall_s": 1.2,
                "mem": {"live_bytes": 50_000_000,
                        "peak_bytes": 100_000_000},
            },
            "returned": 0, "records": [],
        }

    console = Console()
    console.frame(Snapshot(engine=engine(100, 10, 4)))
    out = console.frame(Snapshot(engine=engine(180, 14, 5)))
    assert "engine" in out
    assert "tok/frame     80" in out       # per-frame delta
    assert "steps/frame    4" in out
    # per-frame dispatch economy: Δdispatch_total / Δtokens = 4/80
    assert "disp/tok  0.05" in out
    assert "retraces     5" in out and "+1/frame" in out
    assert "host-stall  42.0%" in out
    assert "mem [" in out and "50/100 MB (peak)" in out
    # profiler off (or old server): section absent, frame still renders
    assert "engine " not in Console().frame(Snapshot())


def test_console_renders_alerts_row():
    """The fleet-health section (serving /debug/health): firing rules
    with severity+reason and the per-frame delta of alert firing
    transitions — a rule that fired and cleared between frames still
    shows as +N."""
    from infinistore_tpu.top import Console, Snapshot

    def health(fired, firing):
        return {
            "enabled": True, "step_s": 1.0, "ticks": 120,
            "probe_errors": 0, "alerts_fired": fired,
            "firing": firing,
            "alerts": {
                "ttft_burn": {"state": "firing" if "ttft_burn" in firing
                              else "ok", "severity": "page",
                              "reason": "burning 5.0x (60s) / 3.1x "
                                        "(600s) of the 10% error budget",
                              "fired": fired},
                "circuit_flap": {"state": "ok", "severity": "page",
                                 "fired": 0},
            },
            "transitions": [], "series": ["serve.finished"],
        }

    console = Console()
    console.frame(Snapshot(health=health(1, [])))
    out = console.frame(Snapshot(health=health(3, ["ttft_burn"])))
    assert "alerts   firing   1" in out
    assert "fired    3 (+2/frame)" in out
    assert "! ttft_burn" in out and "[page]" in out
    assert "burning 5.0x" in out
    # health plane off (ISTPU_HEALTH=0 / old server): row absent
    assert "alerts   firing" not in Console().frame(Snapshot())


def test_console_renders_admission_row():
    """The admission-control section (serving /debug/admission): mode,
    per-frame shed/throttle deltas, the active shed-lane ladder, and a
    per-tenant quota usage bar."""
    from infinistore_tpu.top import Console, Snapshot

    def admission(shed, throttled, mode="shed"):
        return {
            "enabled": True, "mode": mode,
            "burn": {"value": 4.2,
                     "shed_lanes": ["0", "3"] if mode == "shed" else []},
            "shed_total": shed,
            "retry_after_last_s": 2.5,
            "prefill_throttle": {"active": mode == "shed",
                                 "budget_tokens": 64},
            "quota": {
                "throttled_total": throttled,
                "tenants": {"10": {"rate_toks_per_s": 500.0,
                                   "burst_tokens": 1000.0,
                                   "available": 380.0,
                                   "used_frac": 0.62,
                                   "throttled": throttled}},
            },
        }

    console = Console()
    console.frame(Snapshot(admission=admission(5, 1)))
    out = console.frame(Snapshot(admission=admission(9, 3)))
    assert "admission  mode shed" in out
    assert "shed     9 (+4/frame)" in out
    assert "throttled    3 (+2/frame)" in out
    assert "shedding lanes: 0,3" in out
    assert "prefill-cap 64 tok/step" in out
    assert "retry-after 2.5s" in out
    # per-tenant quota usage bar
    assert "quota 10" in out and "62.0% used" in out
    assert "500 tok/s" in out
    # controller off (ISTPU_ADMISSION=0 / old server): row absent
    assert "admission  mode" not in Console().frame(Snapshot())
    assert "admission  mode" not in Console().frame(
        Snapshot(admission={"enabled": False}))


def test_sparkline_and_bar_helpers():
    from infinistore_tpu.top import bar, fmt_dur, sparkline

    assert sparkline([], 8) == "·" * 8
    line = sparkline([0.0, 0.5, 1.0], 3)
    assert len(line) == 3 and line[-1] == "█"
    assert bar(0.5, 10).count("█") == 5
    assert fmt_dur(None).strip() == "-"
    assert fmt_dur(0.0005).endswith("µ")
    assert fmt_dur(0.05).endswith("m")
    assert fmt_dur(2.0).endswith("s")


# ---------------------------------------------------------------------------
# live halves: --once against a real store manage plane; --json-out
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def live_store():
    port, mport = _free_port(), _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", "python"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    deadline = time.time() + 25
    for p in (port, mport):
        while True:
            if proc.poll() is not None:
                pytest.fail("store server failed to start")
            try:
                socket.create_connection(("127.0.0.1", p), timeout=0.5).close()
                break
            except OSError:
                if time.time() >= deadline:
                    proc.kill()
                    pytest.fail("server did not come up")
                time.sleep(0.1)
    yield port, mport
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_top_once_against_live_store(live_store):
    _port, mport = live_store
    r = subprocess.run(
        [sys.executable, "-m", "infinistore_tpu.top",
         "--store-url", f"http://127.0.0.1:{mport}", "--once"],
        capture_output=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    out = r.stdout.decode()
    assert r.returncode == 0, r.stderr.decode()
    assert "istpu-top" in out
    assert "pool occupancy" in out
    assert "store:ok" in out
    assert "serve:-" in out  # unreachable half renders as '-'


def test_benchmark_json_out_schema(live_store, tmp_path, monkeypatch):
    port, _ = live_store
    out_file = tmp_path / "bench.json"
    r = subprocess.run(
        [sys.executable, "-m", "infinistore_tpu.benchmark",
         "--shm", "--service-port", str(port),
         "--size", "4", "--block-size", "16", "--iteration", "2",
         "--json-out", str(out_file)],
        capture_output=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "ISTPU_CLIENT": "python"},
    )
    assert r.returncode == 0, r.stderr.decode()
    rec = json.loads(out_file.read_text())
    # the stable schema contract (docs/observability.md)
    assert set(rec) >= {"run_id", "gbps_put", "gbps_get", "alloc_ms",
                        "stages"}
    assert rec["gbps_put"] > 0 and rec["gbps_get"] > 0
    assert isinstance(rec["run_id"], str) and rec["run_id"]
    assert "write_cache.alloc" in rec["stages"]
    assert rec["alloc_ms"] == rec["stages"]["write_cache.alloc"]["p50_ms"]
    for stage in rec["stages"].values():
        assert {"count", "avg_ms", "p50_ms", "p99_ms", "max_ms"} <= set(stage)


def test_bench_json_helper_is_stable():
    from infinistore_tpu.benchmark import bench_json

    rec = bench_json("abc", 4.0, 5.0, {})
    assert rec == {"run_id": "abc", "gbps_put": 4.0, "gbps_get": 5.0,
                   "alloc_ms": 0.0, "stages": {}}


# ---------------------------------------------------------------------------
# structured logging: records carry the active trace id
# ---------------------------------------------------------------------------


def test_log_lines_carry_trace_id():
    from infinistore_tpu.utils import tracing
    from infinistore_tpu.utils.logging import Logger, _TraceFormatter, \
        TraceContextFilter

    logger = logging.getLogger("infinistore_tpu")
    stream = io.StringIO()
    h = logging.StreamHandler(stream)
    h.setFormatter(_TraceFormatter("[%(levelname)s] %(message)s"))
    logger.addHandler(h)
    # the package logger's LEVEL is shared process state BY DESIGN
    # (Logger.set_log_level; every InfinityConnection(..., log_level=)
    # calls it) — an earlier test file that built connections with
    # log_level="error" (test_trace_wire does) leaves the logger above
    # WARNING and this test's records would be dropped before the
    # handler.  Pin the level for the assertion and restore it after
    # (docs/robustness.md triage note).
    prev_level = logger.level
    logger.setLevel(logging.WARNING)
    try:
        Logger.warn("outside any trace")
        with tracing.trace("logged.request") as tr:
            Logger.warn("inside the trace")
            # the streamer's direct logging.getLogger path is covered too
            logging.getLogger("infinistore_tpu").warning("direct logger")
        trace_id = tr.trace_id
    finally:
        logger.removeHandler(h)
        logger.setLevel(prev_level)
    lines = stream.getvalue().splitlines()
    assert lines[0] == "[WARNING] outside any trace"  # no suffix, no '-'
    assert lines[1] == f"[WARNING] inside the trace trace_id={trace_id}"
    assert lines[2] == f"[WARNING] direct logger trace_id={trace_id}"
    # every record passed the filter (attribute always present)
    rec = logging.LogRecord("infinistore_tpu", logging.INFO, __file__, 1,
                            "x", (), None)
    assert TraceContextFilter().filter(rec) and rec.trace_id == "-"


# ---------------------------------------------------------------------------
# metrics <-> docs drift lint (the CI step, run as a test too)
# ---------------------------------------------------------------------------


def test_metrics_docs_lint_passes():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "metrics_docs_lint.py")],
        capture_output=True, timeout=60,
    )
    assert r.returncode == 0, r.stdout.decode() + r.stderr.decode()


def test_metrics_docs_lint_catches_drift(tmp_path, monkeypatch):
    """The lint actually FAILS on drift — both directions."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import metrics_docs_lint as lint
    finally:
        sys.path.pop(0)
    registered = lint.registered_families()
    assert "istpu_cache_reuse_distance_seconds" in registered
    assert "istpu_engine_prefix_tokens_total" in registered
    docs = (lint.DOCS).read_text()
    documented = lint.documented_families(docs, registered)
    assert registered == documented  # in sync right now
    # a family the docs never mention -> undocumented drift
    assert "istpu_made_up_total" not in documented
    # label-brace annotations don't explode into fake names
    toks = lint.documented_families(
        "`istpu_spec_kind{kind}` and `istpu_serve_{queue_wait,prefill}"
        "_p{50,99}_ms`", registered)
    assert "istpu_spec_kind" in toks
    assert "istpu_serve_queue_wait_p99_ms" in toks
    assert not any(t.endswith("kindkind") for t in toks)
