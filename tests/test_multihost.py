"""REAL two-process multi-host topology (VERDICT r4 missing #3).

Boots a store server plus TWO worker processes that
``jax.distributed.initialize`` against a shared coordinator (4 virtual
CPU devices each -> one 8-device global mesh).  Asserts the three things
the in-process dryrun could not prove:

* the hybrid dp(DCN) x tp mesh runs the full sharded train step with
  collectives crossing the PROCESS boundary (identical finite losses on
  both ranks — the dp psum is the cross-process edge);
* dp-over-DCN serving: rank 1's prefill hits rank 0's store-resident
  prefix over TCP (reused_chunks == full prompt), no recompute;
* both ranks' decoded tokens are identical to each other and to a
  single-process reference engine.

Reference counterpart: the N-node cluster deployment of
``docs/source/design.rst:46-63`` (NCCL/MPI ranks + RDMA fabric), here as
jax.distributed ranks + the store's TCP transport.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_multihost_train_and_serve(tmp_path):
    if os.environ.get("ISTPU_TEST_TPU"):
        # the workers are CPU subprocesses by construction; the final
        # in-process reference would run on the real chip and bf16/f32
        # matmul-precision drift could flip a TINY argmax vs the CPU
        # ranks — this topology test is CPU-mode only
        pytest.skip("multi-process topology test runs in CPU mode")
    store_port, mport, coord = _free_port(), _free_port(), _free_port()
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }
    # the axon plugin's sitecustomize hook can hang interpreter start
    # while its tunnel is wedged; none of these processes need it
    env.pop("PALLAS_AXON_POOL_IPS", None)
    store = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(store_port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16"],
        env=env, cwd=REPO,
    )
    workers = []
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                socket.create_connection(
                    ("127.0.0.1", store_port), timeout=0.5).close()
                break
            except OSError:
                time.sleep(0.1)
        outs = [tmp_path / "r0.json", tmp_path / "r1.json"]
        for pid in (0, 1):
            workers.append(subprocess.Popen(
                [sys.executable, "examples/multihost_worker.py",
                 "--process-id", str(pid), "--num-processes", "2",
                 "--coordinator-port", str(coord),
                 "--store-port", str(store_port),
                 "--out", str(outs[pid])],
                env=env, cwd=REPO,
            ))
        for w in workers:
            assert w.wait(timeout=360) == 0
        r0 = json.loads(outs[0].read_text())
        r1 = json.loads(outs[1].read_text())

        # one GLOBAL mesh across both processes
        assert r0["n_global_devices"] == 8 == r1["n_global_devices"]
        assert r0["mesh_shape"]["tp"] == 2
        # global dp (2 per process x 2 processes over DCN) x tp = 8
        assert r0["mesh_shape"]["dp"] == 4
        assert r0["mesh_shape"] == r1["mesh_shape"]

        # the dp psum crossed processes: both ranks computed the SAME
        # finite loss trajectory, and training moved it
        assert r0["losses"] == pytest.approx(r1["losses"], rel=1e-5)
        assert all(l == l and l < 1e9 for l in r0["losses"])  # finite
        assert r0["losses"][1] < r0["losses"][0]

        # store-mediated prefix reuse across ranks (TCP = DCN analog):
        # rank 0 computed, rank 1 reused every complete chunk
        assert r0["reused_chunks"] == 0
        assert r1["reused_chunks"] == 10 // 4  # both complete chunks, T=4

        # identical serving outputs across ranks...
        assert r0["tokens"] == r1["tokens"]
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
        store.send_signal(signal.SIGINT)
        try:
            store.wait(timeout=10)
        except subprocess.TimeoutExpired:
            store.kill()

    # ...and identical to a single-process reference engine
    import jax
    import numpy as np

    from infinistore_tpu.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models import TINY, init_params, scaled

    cfg = scaled(TINY, dtype=np.float32)
    params = init_params(cfg, jax.random.PRNGKey(7))
    eng = InferenceEngine(params, cfg, PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, n_blocks=64, block_tokens=4,
        dtype=cfg.dtype,
    ))
    want = eng.generate([11, 42, 7, 99, 5, 3, 17, 28, 64, 1], 12)
    assert r0["tokens"] == want
