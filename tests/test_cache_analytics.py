"""KV-cache efficiency analytics: reuse-distance / eviction-age
attribution, dead-on-arrival accounting, the `/debug/cache` report, and
the engine's local-vs-store prefix-hit token counters.

The scripted-workload tests drive the store through an INJECTED clock
(``Store._clock``), so the asserted reuse distances and eviction ages
land in exact histogram buckets — the acceptance criterion's "known
reuse pattern → asserted buckets", with no sleeps and no flake."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from infinistore_tpu import protocol as P
from infinistore_tpu.config import ServerConfig
from infinistore_tpu.pyserver import StoreServer
from infinistore_tpu.utils import metrics as m
from infinistore_tpu.utils.metrics import AGE_BUCKETS


def make_server(block_kb=16, pool_mb=1):
    """An in-process StoreServer (registry + store, no sockets) over a
    hand-built tiny-pool Store — the registry wiring (histogram sinks,
    fn-backed counters) is part of what's under test."""
    from collections import OrderedDict

    from infinistore_tpu.mempool import MM
    from infinistore_tpu.store import CacheAnalytics, Stats, Store

    cfg = ServerConfig(service_port=1, manage_port=1, prealloc_size=1,
                       minimal_allocate_size=block_kb)
    store = Store.__new__(Store)
    store.config = cfg
    store.mm = MM(pool_size=pool_mb << 20, block_size=block_kb << 10)
    store.kv = OrderedDict()
    store.pending = {}
    store._deferred = []
    store.stats = Stats()
    store.disk = None
    store._clock = time.monotonic
    store.analytics = CacheAnalytics()
    store._init_integrity(cfg)  # integrity plane state (epoch, backlog)
    return StoreServer(cfg, store=store)


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _put(store, key, nbytes=64):
    assert store.put_inline(key, b"x" * nbytes) == P.FINISH


def test_reuse_distance_and_eviction_age_buckets():
    """Known reuse pattern → exact bucket assertions, via the scrape."""
    srv = make_server()
    store = srv.store
    clk = Clock()
    store._clock = clk

    _put(store, b"hot")
    _put(store, b"cold")
    _put(store, b"doa")  # never read: must count dead-on-arrival

    # reads at known distances: hot at +0.1s then +0.1s again; cold once
    # at +60s from commit
    clk.t += 0.1
    assert store.get_inline(b"hot") is not None
    clk.t += 0.1
    assert store.get_inline(b"hot") is not None
    clk.t += 59.8
    assert store.get_inline(b"cold") is not None

    # evict everything not leased: ages are now deterministic —
    # hot: 60s since last read, cold: 0s, doa: 60s since commit
    clk.t += 0.0
    store.evict(0.0, 0.0)

    text = srv.metrics_text()
    fams = m.parse_prometheus_text(text)

    def bucket(name, le):
        return fams[(f"{name}_bucket", (("le", f"{le:.10g}"),))]

    # the two 0.1s reuses land in the first bucket >= 0.1 (0.2: bucket 1)
    # and the 60s reuse crosses into the >=51.2 buckets
    assert fams[("istpu_cache_reuse_distance_seconds_count", ())] == 3
    assert bucket("istpu_cache_reuse_distance_seconds", AGE_BUCKETS[1]) == 2
    assert bucket("istpu_cache_reuse_distance_seconds", AGE_BUCKETS[5]) == 2
    assert bucket("istpu_cache_reuse_distance_seconds", AGE_BUCKETS[6]) == 3
    # eviction ages: one ~0s (cold, just read), two 59.9-60s
    assert fams[("istpu_cache_evicted_age_seconds_count", ())] == 3
    assert bucket("istpu_cache_evicted_age_seconds", AGE_BUCKETS[0]) == 1
    assert bucket("istpu_cache_evicted_age_seconds", AGE_BUCKETS[6]) == 3
    # exactly ONE entry died unread
    assert fams[("istpu_cache_dead_on_arrival_total", ())] == 1
    assert store.analytics.evicted_read == 2
    store.close()


def test_cache_report_hot_cold_and_age_bands():
    srv = make_server()
    store = srv.store
    clk = Clock()
    store._clock = clk

    for i in range(4):
        _put(store, f"k{i}".encode())
    # k0 is hot (3 reads), k1 warm (1 read), k2/k3 untouched
    for _ in range(3):
        clk.t += 0.05
        assert store.get_inline(b"k0") is not None
    clk.t += 0.05
    assert store.get_inline(b"k1") is not None
    clk.t += 30.0  # everything ages 30s; k2/k3 are now cold

    rep = store.cache_report(top_n=2)
    assert rep["entries"] == 4
    assert rep["hot"][0]["key"] == "k0" and rep["hot"][0]["hits"] == 3
    assert len(rep["hot"]) == 2  # top_n honored
    cold_keys = {r["key"] for r in rep["cold"]}
    assert cold_keys <= {"k2", "k3"}, cold_keys
    assert rep["hits"] == 4 and rep["misses"] == 0 and rep["hit_ratio"] == 1.0
    bands = rep["age_bands"]
    assert bands["<1m"]["entries"] == 4  # all last-touched 30s ago
    assert bands["<1s"]["entries"] == 0
    assert rep["dead_on_arrival"] == 0

    # a miss shows up in the ratio
    assert store.get_inline(b"nope") is None
    rep = store.cache_report()
    assert rep["misses"] == 1 and rep["hit_ratio"] == pytest.approx(0.8)
    store.close()


def test_stats_dict_carries_dead_on_arrival():
    srv = make_server()
    store = srv.store
    clk = Clock()
    store._clock = clk
    _put(store, b"unread")
    clk.t += 5.0
    store.evict(0.0, 0.0)
    assert store.stats_dict()["dead_on_arrival"] == 1
    # and the flat exposition carries it for the native-backend fallback
    assert "infinistore_tpu_dead_on_arrival 1" in srv.metrics_text()
    store.close()


# ---------------------------------------------------------------------------
# /debug/cache over HTTP + engine provenance counters (live store)
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def live_store():
    port, mport = _free_port(), _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", "python"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    deadline = time.time() + 25
    for p in (port, mport):
        while True:
            if proc.poll() is not None:
                pytest.fail("store server failed to start")
            try:
                socket.create_connection(("127.0.0.1", p), timeout=0.5).close()
                break
            except OSError:
                if time.time() >= deadline:
                    proc.kill()
                    pytest.fail("server did not come up")
                time.sleep(0.1)
    yield port, mport
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_debug_cache_endpoint_live(live_store, monkeypatch):
    monkeypatch.setenv("ISTPU_CLIENT", "python")
    import infinistore_tpu as ist

    port, mport = live_store
    conn = ist.InfinityConnection(ist.ClientConfig(
        host_addr="127.0.0.1", service_port=port,
        connection_type=ist.TYPE_SHM, log_level="error"))
    conn.connect()
    blk = 16 << 10
    buf = np.random.randint(0, 256, 4 * blk, dtype=np.uint8)
    conn.register_mr(buf)
    blocks = [(f"dbg-{i}", i * blk) for i in range(4)]
    conn.write_cache(blocks, blk, buf.ctypes.data)
    dst = np.zeros_like(buf)
    conn.register_mr(dst)
    conn.read_cache(blocks, blk, dst.ctypes.data)
    conn.read_cache([blocks[0]], blk, dst.ctypes.data)  # dbg-0 is hottest

    with urllib.request.urlopen(
        f"http://127.0.0.1:{mport}/debug/cache?n=2", timeout=10
    ) as r:
        rep = json.load(r)
    assert rep["entries"] >= 4 and rep["hits"] >= 5
    assert len(rep["hot"]) == 2
    assert rep["hot"][0]["key"] == "dbg-0"
    assert rep["hot"][0]["hits"] == 2
    assert "age_bands" in rep and "hit_ratio" in rep

    # the histogram families ride the live /metrics too
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mport}/metrics", timeout=10
    ) as r:
        fams = m.parse_prometheus_text(r.read().decode())
    assert fams[("istpu_cache_reuse_distance_seconds_count", ())] >= 5
    conn.close()


def test_engine_prefix_provenance_counters(live_store, monkeypatch):
    """The admission-path split: a prompt whose prefix lives in the STORE
    (seeded by a producer engine) counts store tokens on the consumer; a
    REPEATED prompt counts local tokens; fresh prompts count computed."""
    monkeypatch.setenv("ISTPU_CLIENT", "python")
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    import infinistore_tpu as ist
    from infinistore_tpu.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models import TINY, init_params, scaled

    cfg = scaled(TINY, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(7))
    T = 4
    pc = lambda: PagedCacheConfig(  # noqa: E731
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, n_blocks=64, block_tokens=T, dtype=cfg.dtype)

    def counters():
        fams = m.parse_prometheus_text(
            m.default_registry().to_prometheus_text())
        return {
            src: fams.get(("istpu_engine_prefix_tokens_total",
                           (("source", src),)), 0.0)
            for src in ("local", "store", "computed")
        }

    port, _ = live_store

    def connect():
        c = ist.InfinityConnection(ist.ClientConfig(
            host_addr="127.0.0.1", service_port=port,
            connection_type=ist.TYPE_SHM, log_level="error"))
        c.connect()
        return c

    prompt = [9, 3, 7, 1, 5, 2, 8, 6, 4, 11, 13]  # 11 tokens, T=4

    prod_conn = connect()
    producer = InferenceEngine(params, cfg, pc(), conn=prod_conn,
                               model_id="prov-test")
    before = counters()
    producer.release(producer.prefill(prompt))
    producer.store_flush()
    after_prod = counters()
    # a cold engine + empty store: everything computed
    assert after_prod["computed"] - before["computed"] == len(prompt)

    cons_conn = connect()
    consumer = InferenceEngine(params, cfg, pc(), conn=cons_conn,
                               model_id="prov-test")
    st = consumer.prefill(prompt)
    after_store = counters()
    # the consumer found the producer's chunks in the STORE: 2 complete
    # chunks are reusable ((11-1)//4 = 2), 3 tokens of the tail computed
    assert after_store["store"] - after_prod["store"] == 2 * T
    assert after_store["computed"] - after_prod["computed"] == len(prompt) - 2 * T
    consumer.release(st)

    st = consumer.prefill(prompt)  # repeat: now the LOCAL prefix cache hits
    after_local = counters()
    assert after_local["local"] - after_store["local"] == 2 * T
    assert after_local["store"] == after_store["store"]
    consumer.release(st)

    prod_conn.close()
    cons_conn.close()
