#!/usr/bin/env bash
# Build a distributable wheel with the native runtime compiled in
# (reference parity: build_manylinux_wheels.sh drives docker+cmake; a TPU-VM
# fleet shares one image, so a plain host build is the equivalent).
#
# The wheel bundles infinistore_tpu/libistpu.so (built by setup.py's
# build_py hook from src/); installs fall back to the pure-Python runtime
# when the target host lacks the library.
set -euo pipefail
cd "$(dirname "$0")"

rm -rf build dist infinistore_tpu.egg-info
# --no-isolation/--no-build-isolation: build against the host env (TPU-VM
# images are airgapped; setuptools is baked in)
if python -c "import build" 2>/dev/null; then
    python -m build --wheel --no-isolation
else
    python -m pip wheel . -w dist/ --no-deps --no-build-isolation
fi
ls -l dist/*.whl
echo "smoke-testing the wheel in a scratch prefix..."
tmp=$(mktemp -d)
trap 'rm -rf "$tmp"' EXIT
python -m pip install --quiet --target "$tmp" dist/*.whl --no-deps
# run from the scratch prefix so cwd-relative import resolution (and any
# Python >= 3.10) provably picks the INSTALLED wheel, never the repo tree
( cd "$tmp" && ISTPU_WHEEL_DIR="$tmp" python - <<'EOF'
import os
import infinistore_tpu as ist
from infinistore_tpu import _native
assert ist.__file__.startswith(os.environ["ISTPU_WHEEL_DIR"]), ist.__file__
print("wheel import ok; native runtime available:", _native.available())
EOF
)
