#!/usr/bin/env bash
# Test runner (reference parity: run_test.sh).  Runs the full suite — the
# store integration tests parametrize over both server backends (python
# asyncio + native C++ epoll) and both client implementations.
set -euo pipefail
cd "$(dirname "$0")"

# Build the native runtime up front so its absence is loud, not silently
# skipped by the graceful-fallback path.
make -C src

# JAX surfaces run on a virtual 8-device CPU mesh (conftest pins the
# platform); the real-TPU kernel tests auto-skip without a TPU.
#
# The axon PJRT plugin registers itself at interpreter start via
# sitecustomize (gated on PALLAS_AXON_POOL_IPS) and can HANG every
# python process while its tunnel is wedged.  The CPU suite never needs
# it, so drop the gate unless the caller explicitly wants the on-chip
# Mosaic tests (ISTPU_TEST_TPU=1, which require the axon backend).
# (same truthiness as conftest.py: any non-empty value = TPU mode)
if [[ -z "${ISTPU_TEST_TPU:-}" ]]; then
    exec env -u PALLAS_AXON_POOL_IPS python -m pytest tests/ -q "$@"
fi
exec python -m pytest tests/ -q "$@"
