#!/usr/bin/env bash
# Test runner (reference parity: run_test.sh).  Runs the full suite — the
# store integration tests parametrize over both server backends (python
# asyncio + native C++ epoll) and both client implementations.
set -euo pipefail
cd "$(dirname "$0")"

# Build the native runtime up front so its absence is loud, not silently
# skipped by the graceful-fallback path.
make -C src

# JAX surfaces run on a virtual 8-device CPU mesh (conftest pins the
# platform); the real-TPU kernel tests auto-skip without a TPU.
exec python -m pytest tests/ -q "$@"
