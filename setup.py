"""Build hook: compile the native runtime (libistpu.so) into the package.

The reference's setup.py drives CMake to build its pybind11 extension
(reference: setup.py CMakeBuild); ours drives the plain Makefile in src/ and
ships the resulting shared library as package data — the Python side loads
it via ctypes (infinistore_tpu/_native.py) and falls back to the pure-Python
runtime when no toolchain was available at install time.
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildNativeThenPy(build_py):
    def run(self):
        root = os.path.dirname(os.path.abspath(__file__))
        src = os.path.join(root, "src")
        if shutil.which("make") and shutil.which(os.environ.get("CXX", "g++")):
            subprocess.run(["make", "-C", src], check=True)
        else:
            print("[infinistore-tpu] no C++ toolchain; installing pure-Python runtime")
        super().run()


setup(cmdclass={"build_py": BuildNativeThenPy})
