"""Driver benchmark: Llama-3-8B-shaped KV block put/get bandwidth.

Workload (SURVEY.md §6 config 2): pages of Llama-3-8B KV cache — 32 layers,
8 KV heads, 128 head dim, bf16, 16-token chunks → 64 KiB per (layer, chunk)
page — moved between a client buffer and a live infinistore-tpu server on the
same host (the TPU-VM serving topology).

Measured path: the zero-copy SHM transport (our RDMA analog).
Baseline path:  single-stream loopback TCP inline transfer — the proxy for
the reference's TCP transport measured on identical hardware (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from infinistore_tpu import ClientConfig, InfinityConnection  # noqa: E402
from infinistore_tpu.config import TYPE_SHM, TYPE_TCP  # noqa: E402

PAGE_BYTES = 2 * 16 * 8 * 128 * 2  # K+V, 16 tok, 8 kv-heads, 128 dim, bf16 = 64 KiB
N_LAYERS = 32
CHUNKS = 64  # pages per layer per round -> 128 MiB per round
ROUND_BYTES = PAGE_BYTES * N_LAYERS * CHUNKS


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def start_server():
    service, manage = _free_port(), _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "infinistore_tpu.server",
            "--service-port", str(service), "--manage-port", str(manage),
            "--prealloc-size", "2", "--minimal-allocate-size", "64",
            "--log-level", "warning", "--auto-increase",
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", service), timeout=1).close()
            return proc, service
        except OSError:
            time.sleep(0.2)
    proc.kill()
    raise RuntimeError("server did not come up")


def bench_conn(conn_type: str, port: int, rounds: int, tag: str,
               force_python: bool = False):
    cfg = ClientConfig(host_addr="127.0.0.1", service_port=port,
                       connection_type=conn_type, log_level="warning")
    if force_python:
        # the baseline leg is a stable proxy for the reference's single-stream
        # loopback TCP (BASELINE.md); pin it to the Python client so it does
        # not drift with native-client optimizations
        from infinistore_tpu.lib import Connection

        conn = InfinityConnection.__new__(InfinityConnection)
        conn.config = cfg
        conn.conn = Connection(cfg)
        conn.rdma_connected = False
        import asyncio

        conn.semaphore = asyncio.BoundedSemaphore(128)
    else:
        conn = InfinityConnection(cfg)
    conn.connect()
    buf = np.random.randint(0, 256, size=ROUND_BYTES, dtype=np.uint8)
    conn.register_mr(buf)
    ptr = buf.ctypes.data

    put_t = get_t = 0.0
    for r in range(rounds):
        blocks = [
            (f"{tag}-r{r}-L{layer}-c{c}", (layer * CHUNKS + c) * PAGE_BYTES)
            for layer in range(N_LAYERS)
            for c in range(CHUNKS)
        ]
        t0 = time.perf_counter()
        conn.write_cache(blocks, PAGE_BYTES, ptr)
        put_t += time.perf_counter() - t0
        t0 = time.perf_counter()
        conn.read_cache(blocks, PAGE_BYTES, ptr)
        get_t += time.perf_counter() - t0
        conn.delete_keys([k for k, _ in blocks])
    conn.close()
    gb = rounds * ROUND_BYTES / 1e9
    return gb / put_t, gb / get_t


def main():
    proc, port = start_server()
    try:
        # warmup (compilation-free path, but page in the pools)
        bench_conn(TYPE_SHM, port, 1, "warm")
        shm_put, shm_get = bench_conn(TYPE_SHM, port, 6, "shm")
        tcp_put, tcp_get = bench_conn(TYPE_TCP, port, 2, "tcp", force_python=True)
    finally:
        proc.terminate()
        proc.wait(timeout=10)

    shm_bw = 2 / (1 / shm_put + 1 / shm_get)  # harmonic mean put/get
    tcp_bw = 2 / (1 / tcp_put + 1 / tcp_get)
    print(
        f"# shm put {shm_put:.2f} get {shm_get:.2f} GB/s | "
        f"tcp put {tcp_put:.2f} get {tcp_get:.2f} GB/s",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "llama8b_kv_put_get_bandwidth_shm",
        "value": round(shm_bw, 3),
        "unit": "GB/s",
        "vs_baseline": round(shm_bw / tcp_bw, 2),
    }))


if __name__ == "__main__":
    main()
