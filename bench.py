"""Driver benchmark: Llama-3-8B-shaped KV block put/get bandwidth.

Workload (SURVEY.md §6 config 2): pages of Llama-3-8B KV cache — 32 layers,
8 KV heads, 128 head dim, bf16, 16-token chunks → 64 KiB per (layer, chunk)
page — moved between a client buffer and a live infinistore-tpu server on the
same host (the TPU-VM serving topology).

Measured path: the zero-copy SHM transport (our RDMA analog).
Baseline path:  single-stream loopback TCP inline transfer — the proxy for
the reference's TCP transport measured on identical hardware (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from infinistore_tpu import ClientConfig, InfinityConnection  # noqa: E402
from infinistore_tpu.config import TYPE_SHM, TYPE_TCP  # noqa: E402

PAGE_BYTES = 2 * 16 * 8 * 128 * 2  # K+V, 16 tok, 8 kv-heads, 128 dim, bf16 = 64 KiB
N_LAYERS = 32
CHUNKS = 64  # pages per layer per round -> 128 MiB per round
ROUND_BYTES = PAGE_BYTES * N_LAYERS * CHUNKS


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def start_server(backend=None):
    service, manage = _free_port(), _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "infinistore_tpu.server",
            "--service-port", str(service), "--manage-port", str(manage),
            "--prealloc-size", "2", "--minimal-allocate-size", "64",
            "--log-level", "warning", "--auto-increase",
        ]
        + (["--backend", backend] if backend else []),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", service), timeout=1).close()
            return proc, service
        except OSError:
            time.sleep(0.2)
    proc.kill()
    raise RuntimeError("server did not come up")


def bench_conn(conn_type: str, port: int, rounds: int, tag: str,
               force_python: bool = False):
    cfg = ClientConfig(host_addr="127.0.0.1", service_port=port,
                       connection_type=conn_type, log_level="warning",
                       # the baseline proxy is the reference's single TCP
                       # stream; the measured path uses the striped default
                       num_streams=1 if force_python else 4)
    if force_python:
        # the baseline leg is a stable proxy for the reference's single-stream
        # loopback TCP (BASELINE.md); pin it to the Python client so it does
        # not drift with native-client optimizations
        prev = os.environ.get("ISTPU_CLIENT")
        os.environ["ISTPU_CLIENT"] = "python"
        try:
            conn = InfinityConnection(cfg)
        finally:
            if prev is None:
                os.environ.pop("ISTPU_CLIENT", None)
            else:
                os.environ["ISTPU_CLIENT"] = prev
    else:
        conn = InfinityConnection(cfg)
    conn.connect()
    buf = np.random.randint(0, 256, size=ROUND_BYTES, dtype=np.uint8)
    conn.register_mr(buf)
    ptr = buf.ctypes.data

    put_t = get_t = 0.0
    for r in range(rounds):
        blocks = [
            (f"{tag}-r{r}-L{layer}-c{c}", (layer * CHUNKS + c) * PAGE_BYTES)
            for layer in range(N_LAYERS)
            for c in range(CHUNKS)
        ]
        t0 = time.perf_counter()
        conn.write_cache(blocks, PAGE_BYTES, ptr)
        put_t += time.perf_counter() - t0
        t0 = time.perf_counter()
        conn.read_cache(blocks, PAGE_BYTES, ptr)
        get_t += time.perf_counter() - t0
        conn.delete_keys([k for k, _ in blocks])
    stages = conn.latency_stats()
    conn.close()
    gb = rounds * ROUND_BYTES / 1e9
    return gb / put_t, gb / get_t, stages


def bench_tpu_leg(timeout_s: int = 1800) -> dict:
    """Run the TPU-in-the-loop leg (bench_tpu.py) in a subprocess with a hard
    timeout: a wedged TPU tunnel must never hang the driver bench.

    The leg's own staged init watchdog bounds a hung PJRT client AND names
    the phase it hung in, so there is no separate probe step.  Returns the
    leg's JSON dict on success, ``{"unavailable": <structured failure
    record>}`` when init hung or found no TPU (surfaced in the bench output
    as ``tpu_unavailable``), or {} on timeout/unparseable output."""
    if os.environ.get("ISTPU_BENCH_TPU") == "0":
        return {"disabled": True}
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_tpu.py")
    # No separate probe: bench_tpu.py's staged init watchdog bounds a wedged
    # tunnel by itself AND names the phase it hung in (round-3's probe loop
    # burned ~5 min to learn only "hung").  Worst case here is one
    # init-timeout; best case recovers the round's hardware numbers.
    try:
        # own process group: on timeout we must also kill the server
        # subprocess bench_tpu spawns (SIGKILL to the leg alone would orphan
        # it, leaking its shm pool)
        leg = subprocess.Popen(
            [sys.executable, script],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            start_new_session=True,
        )
        stdout, stderr = leg.communicate(timeout=timeout_s)
        r = subprocess.CompletedProcess(leg.args, leg.returncode, stdout, stderr)
    except subprocess.TimeoutExpired:
        import signal

        os.killpg(leg.pid, signal.SIGKILL)
        stdout, _ = leg.communicate()
        # salvage the legs that DID finish: bench_tpu prints a cumulative
        # JSON snapshot after every leg
        for line in reversed(stdout.decode(errors="replace").strip().splitlines()):
            try:
                partial = json.loads(line)
            except ValueError:
                continue
            print("# tpu leg: timed out; using partial results", file=sys.stderr)
            partial["leg_timed_out"] = 1
            return partial
        print("# tpu leg: timed out mid-run", file=sys.stderr)
        return {"timed_out": True}
    if r.returncode != 0:
        # structured failure: bench_tpu's watchdog prints a JSON record
        # naming the init phase reached + relay socket picture; fold it (and
        # the stderr tail, which carries the faulthandler stack of the hung
        # init thread) into the bench output so the round's BENCH file
        # documents exactly WHY hardware was unreachable
        stderr_tail = r.stderr.decode(errors="replace")[-1200:]
        print(f"# tpu leg: unavailable ({stderr_tail[-300:].replace(chr(10), ' | ')})",
              file=sys.stderr)
        rec: dict = {}
        for line in reversed(r.stdout.decode(errors="replace").strip().splitlines()):
            try:
                rec = json.loads(line)
                break
            except ValueError:
                continue
        if rec.get("error"):
            rec["stderr_tail"] = stderr_tail
            return {"unavailable": rec}
        return {}
    try:
        return json.loads(r.stdout.decode().strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {}


def bench_cluster(n_nodes: int, rounds: int = 4) -> dict:
    """Cluster leg: N local store instances driven through the
    consistent-hash router (``infinistore_tpu.cluster``), one writer
    thread per node per round — the aggregate number says what the
    fleet sustains when one host's NIC/DRAM stops being the cap, and
    the per-node split shows ring balance."""
    import concurrent.futures as cf

    from infinistore_tpu.cluster import RoutedStorePool

    procs = []
    try:
        for _ in range(n_nodes):
            procs.append(start_server())
        pool = RoutedStorePool(
            [f"127.0.0.1:{port}" for _, port in procs],
            connection_type=TYPE_SHM,
        )
        bufs = {}
        for node in pool.nodes():
            buf = np.random.randint(0, 256, size=ROUND_BYTES, dtype=np.uint8)
            node.conn.register_mr(buf)
            bufs[node.endpoint] = buf
        per_node = {ep: {"put_s": 0.0, "get_s": 0.0, "bytes": 0}
                    for ep in pool.endpoints}
        put_t = get_t = 0.0
        with cf.ThreadPoolExecutor(max_workers=n_nodes) as pool_exec:
            for r in range(rounds):
                keys = [f"cl-r{r}-L{layer}-c{c}"
                        for layer in range(N_LAYERS) for c in range(CHUNKS)]
                groups = pool.partition(keys)

                def one(ep_idxs, op):
                    ep, idxs = ep_idxs
                    blocks = [(keys[i], j * PAGE_BYTES)
                              for j, i in enumerate(idxs)]
                    conn = pool.node(ep).conn
                    t0 = time.perf_counter()
                    getattr(conn, op)(blocks, PAGE_BYTES,
                                      bufs[ep].ctypes.data)
                    dt = time.perf_counter() - t0
                    per_node[ep]["put_s" if op == "write_cache"
                                 else "get_s"] += dt
                    if op == "write_cache":
                        per_node[ep]["bytes"] += PAGE_BYTES * len(blocks)
                    return dt

                t0 = time.perf_counter()
                list(pool_exec.map(lambda g: one(g, "write_cache"),
                                   groups.items()))
                put_t += time.perf_counter() - t0
                t0 = time.perf_counter()
                list(pool_exec.map(lambda g: one(g, "read_cache"),
                                   groups.items()))
                get_t += time.perf_counter() - t0
                for ep, idxs in groups.items():
                    pool.node(ep).conn.delete_keys(
                        [keys[i] for i in idxs])

        pool.close()
        # the native fleet is done — free its CPU before the reshape
        # leg so the two migration passes aren't measured under the
        # native servers' polling load
        for proc, _ in procs:
            proc.terminate()
        for proc, _ in procs:
            proc.wait(timeout=10)
        procs.clear()

        # -- reshape leg: join one spare node into the loaded fleet,
        # once over the pre-PR-16 per-key path (``_copy_batch``
        # disabled) and once over the descriptor-batched path — same
        # key population, same node, so the two ``migrate_gbps``
        # numbers are directly comparable.  The leg runs its own
        # python-backend mini-fleet with a python-client pool
        # (``op_timeout_s``): migration needs the key-listing surface,
        # which neither the native server nor the native client speaks
        for _ in range(n_nodes):
            procs.append(start_server(backend="python"))
        rpool = RoutedStorePool(
            [f"127.0.0.1:{port}" for _, port in procs[-n_nodes:]],
            connection_type=TYPE_SHM, op_timeout_s=30.0, replicas=1,
        )
        for node in rpool.nodes():
            buf = np.random.randint(0, 256, size=ROUND_BYTES,
                                    dtype=np.uint8)
            node.conn.register_mr(buf)
            bufs[node.endpoint] = buf
        mig_keys = [f"mig-L{layer}-c{c}"
                    for layer in range(N_LAYERS) for c in range(CHUNKS)]
        for ep, idxs in rpool.partition(mig_keys).items():
            blocks = [(mig_keys[i], j * PAGE_BYTES)
                      for j, i in enumerate(idxs)]
            rpool.node(ep).conn.write_cache(blocks, PAGE_BYTES,
                                            bufs[ep].ctypes.data)
        spare = start_server(backend="python")
        procs.append(spare)
        spare_ep = f"127.0.0.1:{spare[1]}"

        def _join_and_measure(per_key_only):
            if per_key_only:  # the old path, for the comparison row
                rpool._copy_batch = lambda *a, **kw: None
            try:
                rpool.join_node(spare_ep)
                while not rpool.migration_idle():
                    time.sleep(0.02)
                return rpool.migration_report()
            finally:
                rpool.__dict__.pop("_copy_batch", None)

        rep_new = _join_and_measure(per_key_only=False)
        rpool.drain_node(spare_ep)
        while not rpool.migration_idle():
            time.sleep(0.02)
        # the drained spare still holds the copied bytes — purge so the
        # second join moves real bytes instead of skipping everything
        cfg = ClientConfig(host_addr="127.0.0.1", service_port=spare[1],
                           connection_type=TYPE_SHM, log_level="warning")
        spare_conn = InfinityConnection(cfg)
        spare_conn.connect()
        spare_conn.purge()
        spare_conn.close()
        rep_old = _join_and_measure(per_key_only=True)
        rpool.close()
    finally:
        for proc, _ in procs:
            proc.terminate()
        for proc, _ in procs:
            proc.wait(timeout=10)
    gb = rounds * ROUND_BYTES / 1e9
    return {
        "cluster_nodes": n_nodes,
        "cluster_put_gbps": round(gb / put_t, 3),
        "cluster_get_gbps": round(gb / get_t, 3),
        "migrate_gbps": rep_new.get("migrate_gbps", 0.0),
        "migrate_gbps_per_key": rep_old.get("migrate_gbps", 0.0),
        "migrate_bytes": rep_new.get("bytes", 0),
        "cluster_per_node": {
            ep: {
                "put_gbps": round(s["bytes"] / 1e9 / s["put_s"], 3)
                if s["put_s"] else 0.0,
                "get_gbps": round(s["bytes"] / 1e9 / s["get_s"], 3)
                if s["get_s"] else 0.0,
                "bytes": s["bytes"],
            }
            for ep, s in per_node.items()
        },
    }


def bench_read_latency(port: int, n: int = 400) -> dict:
    """Single-page (64 KiB) read latency percentiles on the zero-copy path —
    the latency half of the driver metric (BASELINE.json: "p50 read
    latency"; VERDICT r2 missing #5)."""
    cfg = ClientConfig(host_addr="127.0.0.1", service_port=port,
                       connection_type=TYPE_SHM, log_level="warning")
    conn = InfinityConnection(cfg)
    conn.connect()
    buf = np.random.randint(0, 256, size=PAGE_BYTES, dtype=np.uint8)
    conn.register_mr(buf)
    ptr = buf.ctypes.data
    conn.write_cache([("lat-page", 0)], PAGE_BYTES, ptr)
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        conn.read_cache([("lat-page", 0)], PAGE_BYTES, ptr)
        ts.append(time.perf_counter() - t0)
    conn.delete_keys(["lat-page"])
    conn.close()
    ts.sort()
    return {
        "p50_read_latency_us": round(ts[n // 2] * 1e6, 1),
        "p99_read_latency_us": round(ts[min(int(n * 0.99), n - 1)] * 1e6, 1),
    }


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser("bench.py")
    ap.add_argument("--json-out", default=None, metavar="FILE",
                    help="also write the stable perf-trajectory record "
                         "({run_id, gbps_put, gbps_get, alloc_ms, "
                         "stages:{...}} — docs/observability.md) for the "
                         "measured SHM leg")
    ap.add_argument("--endpoints", type=int, default=0, metavar="N",
                    help="also run the CLUSTER leg: N local store "
                         "instances driven through the consistent-hash "
                         "router, reporting aggregate and per-node GB/s "
                         "(cluster_put_gbps / cluster_get_gbps)")
    args = ap.parse_args(argv)

    proc, port = start_server()
    try:
        # warmup (compilation-free path, but page in the pools)
        bench_conn(TYPE_SHM, port, 1, "warm")
        shm_put, shm_get, shm_stages = bench_conn(TYPE_SHM, port, 6, "shm")
        tcp_put, tcp_get, _ = bench_conn(TYPE_TCP, port, 2, "tcp", force_python=True)
        lat = bench_read_latency(port)
    finally:
        proc.terminate()
        proc.wait(timeout=10)

    cluster = {}
    if args.endpoints:
        cluster = bench_cluster(args.endpoints)
        print(
            "# cluster x{}: put {} get {} GB/s | per-node {}".format(
                cluster["cluster_nodes"], cluster["cluster_put_gbps"],
                cluster["cluster_get_gbps"],
                {ep: f"{s['put_gbps']}/{s['get_gbps']}"
                 for ep, s in cluster["cluster_per_node"].items()},
            ),
            file=sys.stderr,
        )
        print(
            "# reshape: migrate {} GB/s batched vs {} GB/s per-key "
            "({} bytes moved)".format(
                cluster["migrate_gbps"], cluster["migrate_gbps_per_key"],
                cluster["migrate_bytes"],
            ),
            file=sys.stderr,
        )

    tpu = bench_tpu_leg()
    if not tpu or "unavailable" in tpu or "timed_out" in tpu:
        # Tunnel wedged at bench time: fall back to the last real-chip capture
        # (BENCH_TPU_SNAPSHOT.json, committed mid-round while the TPU answered)
        # and say so — stale numbers are clearly marked, never silently fresh.
        # An explicitly disabled leg (ISTPU_BENCH_TPU=0) stays disabled.
        snap_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_SNAPSHOT.json"
        )
        if "disabled" not in tpu and os.path.exists(snap_path):
            with open(snap_path) as f:
                snap = json.load(f)
            snap.pop("note", None)
            snap["stale"] = True
            snap["live_leg_error"] = (
                tpu.get("unavailable") or tpu.get("timed_out") or "no output"
                if tpu else "no output"
            )
            print("# tpu leg unavailable now; merging committed snapshot "
                  f"captured {snap.get('captured_utc', '?')} (marked stale)",
                  file=sys.stderr)
            tpu = snap

    shm_bw = 2 / (1 / shm_put + 1 / shm_get)  # harmonic mean put/get
    tcp_bw = 2 / (1 / tcp_put + 1 / tcp_get)
    print(
        f"# shm put {shm_put:.2f} get {shm_get:.2f} GB/s | "
        f"tcp put {tcp_put:.2f} get {tcp_get:.2f} GB/s",
        file=sys.stderr,
    )
    if tpu:
        print(f"# tpu leg: {json.dumps(tpu)}", file=sys.stderr)
    result = {
        "metric": "llama8b_kv_put_get_bandwidth_shm",
        "value": round(shm_bw, 3),
        "unit": "GB/s",
        "vs_baseline": round(shm_bw / tcp_bw, 2),
        "shm_put_gbps": round(shm_put, 2),
        "shm_get_gbps": round(shm_get, 2),
        **lat,
        **cluster,
    }
    # extra keys: the TPU-in-the-loop numbers (HBM<->store hop, Pallas vs
    # XLA decode attention on chip, engine tokens/s) when a TPU answered
    result.update({f"tpu_{k}": v for k, v in tpu.items()})
    print(json.dumps(result))
    if args.json_out:
        import uuid

        from infinistore_tpu.benchmark import bench_json

        rec = bench_json(uuid.uuid4().hex[:8], shm_put, shm_get, shm_stages)
        rec.update(lat)  # the latency half rides along (extra keys allowed)
        rec.update(cluster)  # cluster aggregate + per-node, when run
        with open(args.json_out, "w") as f:
            json.dump(rec, f, indent=2)


if __name__ == "__main__":
    main()
