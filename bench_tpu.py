"""TPU-in-the-loop benchmark leg (run by bench.py in a subprocess).

Measures the paths the host-only bench can't (VERDICT round-1 weak #2/#4/#5):

1. the full serving hop between TPU HBM and the store —
   paged-cache -> fused gather -> D2H -> zero-copy put (``save_pages``) and
   get -> H2D -> fused scatter (``load_pages``) — against a live server
   (reference analog: benchmark.py src/dst cuda device selection,
   reference infinistore/benchmark.py:144-247);
2. the Pallas paged-decode attention kernel and the flash prefill kernel vs
   their XLA paths on the real chip (compile acceptance + us/step +
   effective HBM GB/s);
3. end-to-end decode tokens/s for the TINY model through the engine's
   compiled scan loop.

Each leg runs independently: a kernel Mosaic rejection or a store hiccup is
recorded as ``<leg>_error`` in the JSON instead of sinking the other
numbers.  Prints ONE JSON line; exits non-zero only if no TPU is reachable.
bench.py treats failure/timeout as "no TPU leg" and reports host metrics
only, so a wedged TPU tunnel can never hang the driver bench.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _fetch(x) -> float:
    """Ground-truth sync: pull a scalar reduction of ``x`` to the host.
    On the tunneled runtime ``block_until_ready`` can return before the
    work is actually done (measured: a 512-token prefill "completed" in
    6 ms by block_until_ready but took 87 ms to produce its logits), so
    every timed region must end by fetching real data."""
    import jax.numpy as jnp

    return float(jnp.sum(x.astype(jnp.float32)))


def _median_spread(measure, n: int = 3):
    """Run a no-arg measurement ``n`` times -> (median, rel_spread).

    rel_spread = (max - min) / median: the honesty metric VERDICT r4
    weak #1 demanded — every headline leg reports it so a default
    chosen on a noisy single shot can't happen again.  ``measure`` must
    defeat memoization itself (fresh prompts / evolving state)."""
    vals = sorted(measure() for _ in range(max(1, n)))
    med = vals[len(vals) // 2]
    spread = (vals[-1] - vals[0]) / med if med > 0 else 0.0
    return med, round(spread, 3)


def _timeit_chained(step, x0, n=20, budget_s: float = 10.0):
    """Mean seconds/iteration of ``x = step(x, i)``; the chain defeats the
    runtime's memoization of identical dispatches (same executable + same
    input buffers returns a cached result without executing) and the final
    ``_fetch`` defeats optimistic completion — the two measured traps of
    this platform (docs/tpu_perf_notes.md)."""
    x = step(x0, 0)
    t0 = time.perf_counter()
    _fetch(x)
    once = max(time.perf_counter() - t0, 1e-6)
    n = max(3, min(n, int(budget_s / once)))
    t0 = time.perf_counter()
    for i in range(n):
        x = step(x, i + 1)
    _fetch(x)
    return (time.perf_counter() - t0) / n


def leg_decode_kernel(out: dict) -> None:
    """Paged-decode attention kernel measured IN MODEL: the same
    head_dim-128 engine decoding with the Pallas kernel vs forced-XLA
    attention (ISTPU_NO_PALLAS).  Standalone kernel timing is meaningless
    on this platform — per-dispatch relay overhead (~15-20 ms) swamps a
    sub-ms kernel, and constant-input repeat loops hit execution
    memoization (docs/tpu_perf_notes.md) — so the kernel's value is
    measured where it runs: inside the compiled decode scan."""
    import os

    import jax
    import numpy as np

    from infinistore_tpu.engine import engine as eng_mod
    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.llama import scaled, init_params

    cfg = scaled(_bench_model(), n_heads=16, n_kv_heads=8,
                 head_dim_override=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    rng = np.random.RandomState(0)

    def tok_s():
        """Median-of-3 decode tok/s on ONE warmed engine; each repeat
        decodes fresh sequences (evolving state defeats memoization)."""
        eng = InferenceEngine(params, cfg, PagedCacheConfig(
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, block_tokens=16, n_blocks=512,
            dtype="bfloat16",
        ))
        B, n = 8, eng.decode_chunk * 2
        warm = [eng.prefill([int(x) for x in rng.randint(1, cfg.vocab_size, size=64)])
                for _ in range(B)]
        eng.decode_batch(warm, eng.decode_chunk)
        eng.decode_batch(warm, n)
        for s in warm:
            eng.release(s)

        def one() -> float:
            sts = [eng.prefill(
                [int(x) for x in rng.randint(1, cfg.vocab_size, size=64)])
                for _ in range(B)]
            eng.decode_batch(sts, eng.decode_chunk)
            t0 = time.perf_counter()
            eng.decode_batch(sts, n)  # host tokens: ground-truth sync
            r = B * n / (time.perf_counter() - t0)
            for s in sts:
                eng.release(s)
            return r

        return _median_spread(one, 3)

    xla_tok_s, xla_sp = tok_s()  # the default path
    os.environ["ISTPU_PALLAS_DECODE"] = "1"
    eng_mod._JIT_CACHE.clear()  # env is read at trace time; force re-trace
    try:
        pallas_tok_s, pallas_sp = tok_s()
    finally:
        del os.environ["ISTPU_PALLAS_DECODE"]
        eng_mod._JIT_CACHE.clear()
    out["decode128_pallas_tok_s"] = round(pallas_tok_s, 1)
    out["decode128_pallas_spread"] = pallas_sp
    out["decode128_xla_tok_s"] = round(xla_tok_s, 1)
    out["decode128_xla_spread"] = xla_sp
    out["pallas_speedup_vs_xla"] = round(pallas_tok_s / xla_tok_s, 2)


def leg_flash_kernel(out: dict) -> None:
    """Flash prefill kernel measured IN MODEL: TTFT for a 2048-token
    prompt on the head_dim-128 engine with the Pallas flash kernel vs
    forced-XLA attention (same methodology note as leg_decode_kernel)."""
    import os

    import jax
    import numpy as np

    from infinistore_tpu.engine import engine as eng_mod
    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.llama import scaled, init_params

    cfg = scaled(_bench_model(), n_heads=16, n_kv_heads=8,
                 head_dim_override=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    rng = np.random.RandomState(1)

    def bench_backend(S: int):
        """Median-of-3 TTFT (ms) for S-token prompts on ONE warmed
        engine; each repeat prefills a FRESH prompt (memoization trap)
        and releases it (pool stays level)."""
        eng = InferenceEngine(params, cfg, PagedCacheConfig(
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, block_tokens=16, n_blocks=768,
            dtype="bfloat16",
        ))
        w = eng.prefill([int(x) for x in rng.randint(1, cfg.vocab_size, size=S)])
        _fetch(w.last_logits)
        eng.release(w)

        def one() -> float:
            p = [int(x) for x in rng.randint(1, cfg.vocab_size, size=S)]
            t0 = time.perf_counter()
            st = eng.prefill(p)
            _fetch(st.last_logits)
            ms = (time.perf_counter() - t0) * 1e3
            eng.release(st)
            return ms

        return _median_spread(one, 3)

    # smoke runs (ISTPU_BENCH_MODEL=tiny on CPU) shrink the prompt sizes
    # ~8x — same code path, feasible wall time on a 1-core host
    smoke = os.environ.get("ISTPU_BENCH_MODEL") == "tiny"
    sizes = ((256, "2k"), (1024, "8k")) if smoke else (
        (2048, "2k"), (8192, "8k"))
    import contextlib

    @contextlib.contextmanager
    def env_var(name: str, value):
        """Set (value=str) or unset (value=None) ``name`` for the block,
        restore the operator's own value after, and clear the jit cache
        on BOTH transitions — trace-time env reads demand a retrace, and
        a leaked override would silently flip every later leg."""
        prior = os.environ.get(name)
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = value
        eng_mod._JIT_CACHE.clear()
        try:
            yield
        finally:
            if prior is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = prior
            eng_mod._JIT_CACHE.clear()

    for S, tag in sizes:
        # flash is OPT-IN now (the r4-recorded number favored XLA and
        # the default follows the bench); this leg measures both sides
        # regardless of how the operator set the flag globally
        with env_var("ISTPU_PALLAS_PREFILL", "1"):
            flash_ms, flash_sp = bench_backend(S)
        with env_var("ISTPU_PALLAS_PREFILL", None):
            xla_ms, xla_sp = bench_backend(S)
        out[f"flash_prefill_{tag}_ms"] = round(flash_ms, 1)
        out[f"flash_prefill_{tag}_spread"] = flash_sp
        out[f"xla_prefill_{tag}_ms"] = round(xla_ms, 1)
        out[f"xla_prefill_{tag}_spread"] = xla_sp
        out[f"flash_speedup_vs_xla_{tag}"] = round(xla_ms / flash_ms, 2)
    # legacy key (round-4 comparisons)
    out["flash_speedup_vs_xla"] = out["flash_speedup_vs_xla_2k"]


def leg_store_hop(out: dict) -> None:
    """HBM <-> store bandwidth through a live server (Llama-3-8B KV shapes,
    SURVEY §6 config 2; 64 KiB/page/layer, 128 MiB per round)."""
    import jax
    import jax.numpy as jnp

    from infinistore_tpu import ClientConfig, InfinityConnection
    from infinistore_tpu.config import TYPE_SHM
    from infinistore_tpu.kv.cache import PagedCacheConfig, init_cache
    from infinistore_tpu.kv.transfer import KVTransferEngine

    pc = PagedCacheConfig(
        n_layers=32, n_kv_heads=8, head_dim=128, block_tokens=16,
        n_blocks=128, dtype="bfloat16",
    )
    service, manage = _free_port(), _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "infinistore_tpu.server",
            "--service-port", str(service), "--manage-port", str(manage),
            "--prealloc-size", "2", "--minimal-allocate-size", "64",
            "--log-level", "warning", "--auto-increase",
            # the python data plane is the feature-complete one
            # (integrity verification + alloc-first zero-copy pushes both
            # negotiate python<->python only); measuring the native
            # backend here would silently bench the legacy staged path
            "--backend", "python",
        ],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", service), timeout=1).close()
                break
            except OSError:
                time.sleep(0.2)

        conn = InfinityConnection(ClientConfig(
            host_addr="127.0.0.1", service_port=service, connection_type=TYPE_SHM,
            # op_timeout pins the PYTHON client (the runtime that
            # negotiates alloc-first + integrity — the shipping fast
            # path) and bounds any single wedged op on a flaky tunnel
            op_timeout_s=60.0,
        ))
        conn.connect()
        eng = KVTransferEngine(conn, pc)
        cache = init_cache(pc)
        cache = cache + jnp.asarray(0.125, dtype=cache.dtype)  # touch HBM
        cache.block_until_ready()

        n_chunks = 64
        chunk_bytes = pc.page_bytes * pc.n_layers * n_chunks  # 128 MiB
        ids = list(range(n_chunks))

        def put(tag):
            ks = [f"bench-{tag}-{i}" for i in range(n_chunks)]
            t0 = time.perf_counter()
            eng.save_pages(cache, ids, ks)
            return time.perf_counter() - t0, ks

        put("warm")  # compile the gather + first registration
        t_put, keys = put("r0")
        t2, _ = put("r1")
        t_put = min(t_put, t2)

        def get(ks):
            t0 = time.perf_counter()
            c2 = eng.load_pages(cache, ids, ks)
            _fetch(c2[0, 0, 0, 0, 0])  # ground-truth completion, see _fetch
            return time.perf_counter() - t0

        get(keys)  # compile the scatter
        t_get = min(get(keys), get(keys))

        out["hbm_put_gbps"] = round(chunk_bytes / t_put / 1e9, 2)
        out["hbm_get_gbps"] = round(chunk_bytes / t_get / 1e9, 2)

        # per-stage breakdown of the LAST save's push (the transfer
        # records it per push_commit): a regression on this path must be
        # attributable from bench output alone — a slow d2h is the
        # device link, a slow pool_copy is the memcpy/zero-copy fill, a
        # slow alloc/commit is server round-trips.  zero_copy_bands > 0
        # proves the alloc-first direct-to-pool path actually engaged.
        stages = getattr(eng, "last_push_stages", {}) or {}
        for k in ("d2h_s", "pool_copy_s", "alloc_s", "commit_s", "wire_s"):
            if stages.get(k):
                out[f"hbm_put_{k}"] = round(stages[k], 4)
        out["hbm_put_zero_copy_bands"] = stages.get("zero_copy_bands", 0)
        out["hbm_put_staged_bands"] = stages.get("staged_bands", 0)

        # RAW transfer floor alongside (VERDICT r4 weak #4: the
        # "design-bound vs tunnel-bound" split must be IN the JSON, not
        # asserted): plain device_get/device_put of a 64 MiB buffer —
        # no store, no gather, no pool.  If hbm_*_gbps ≈ these floors,
        # the store hop adds nothing and the bottleneck is the link.
        import numpy as _np

        raw = jnp.zeros((32 << 20,), jnp.uint16)  # 64 MiB
        raw = (raw + 1).block_until_ready()
        jax.device_get(raw)  # warm the d2h path
        harr0 = _np.asarray(jax.device_get(raw))
        _fetch(jax.device_put(harr0)[:8])  # warm h2d + the fetch program

        def one_d2h() -> float:
            # fresh buffer per repeat (trap 2), GROUND-TRUTHED before
            # timing (trap 1: block_until_ready returns optimistically
            # here, so the add must be proven done via a data fetch)
            one_d2h.i = getattr(one_d2h, "i", 0) + 1
            r = raw + one_d2h.i
            _fetch(r[:8])
            t0 = time.perf_counter()
            jax.device_get(r)
            return time.perf_counter() - t0

        def one_h2d() -> float:
            one_h2d.i = getattr(one_h2d, "i", 0) + 1
            h = harr0 + one_h2d.i  # fresh host buffer per repeat
            t0 = time.perf_counter()
            dev = jax.device_put(h)
            _fetch(dev[:8])
            return time.perf_counter() - t0

        t_d2h = min(one_d2h() for _ in range(2))
        t_h2d = min(one_h2d() for _ in range(2))
        out["raw_d2h_gbps"] = round(raw.nbytes / t_d2h / 1e9, 3)
        out["raw_h2d_gbps"] = round(raw.nbytes / t_h2d / 1e9, 3)
        conn.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def leg_engine(out: dict) -> None:
    """End-to-end decode tokens/s (TINY) through the compiled scan loop."""
    import jax
    import numpy as np

    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.llama import TINY, init_params

    cfg = TINY
    params = init_params(cfg, jax.random.PRNGKey(0))
    epc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        block_tokens=16, n_blocks=64, dtype="bfloat16",
    )
    eng = InferenceEngine(params, cfg, epc)
    prompt = [int(x) for x in np.arange(1, 33)]
    # full-length warmup: compile every chunk size AND block-table width
    # bucket the timed run will cross (see leg_model_perf)
    w = eng.prefill(prompt)
    eng.decode(w, 64)
    eng.decode(w, 128)
    eng.release(w)
    st = eng.prefill(prompt)
    eng.decode(st, 64)
    t0 = time.perf_counter()
    eng.decode(st, 128)
    dt = time.perf_counter() - t0
    out["decode_tok_s_tiny"] = round(128 / dt, 1)


def leg_serving(out: dict) -> None:
    """Continuous-batching serving throughput (LLAMA3_1B through the
    Scheduler): 16 requests with mixed prompt lengths and budgets admitted
    into one lockstep batch with chunked-prefill interleaving — the
    serving loop's aggregate tokens/s, one level above leg_model_perf's
    raw decode scan (reference analog: the vLLM serving loop the
    reference fronts)."""
    import jax
    import numpy as np

    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.engine.scheduler import Scheduler
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.llama import init_params

    cfg = _bench_model()
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    def mk_sched(stepprof=None):
        eng = InferenceEngine(params, cfg, PagedCacheConfig(
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, block_tokens=16, n_blocks=1024,
            dtype="bfloat16",
        ))
        # max_batch 16: r4 ran this leg at 8, so half the 16-request
        # load WAITED a full earlier generation before admission — the
        # 1131 ms TTFT p50 was ~90% queue-wait by construction.  B=16
        # lockstep decode still fills the chip (decode is HBM-bound;
        # the gather widens, the weights amortize), so admit everything
        # and let TTFT be prefill-bound (VERDICT r4 next #3).
        return Scheduler(eng, max_batch=16, prefill_concurrency=8,
                         stepprof=stepprof)

    rng = np.random.RandomState(7)

    def submit_all(sched):
        total = 0
        for i in range(16):
            S = int((48, 96, 160, 224)[i % 4])
            n = int((64, 96)[i % 2])
            total += n
            sched.submit(
                [int(x) for x in rng.randint(1, cfg.vocab_size, size=S)],
                max_new_tokens=n,
            )
        return total

    # warm pass compiles every (batch-shape, table-width, prefill-bucket)
    # program the measured pass will touch
    warm = mk_sched()
    submit_all(warm)
    warm.run()
    # the measured pass runs under a StepProfiler at DEFAULT sampling —
    # the serving leg now reports the host-stall/device split and
    # retrace pressure next to its tokens/s, so "serving is slow" is
    # attributable from bench output alone (scripts/bench_history.py
    # trends host_stall_frac / retraces_per_100_steps)
    from infinistore_tpu.engine.stepprof import StepProfiler

    prof = StepProfiler()
    sched = mk_sched(stepprof=prof)
    t_submit: dict = {}
    t_first: dict = {}

    def mk_on_token(slot):
        # called at chunk granularity; the first delivery marks the
        # request's TTFT (queueing + prompt ingestion + first chunk)
        def cb(toks, done):
            if slot not in t_first and toks:
                t_first[slot] = time.perf_counter()
        return cb

    total = 0
    rng2 = np.random.RandomState(7)
    t0 = time.perf_counter()
    for i in range(16):
        S = int((48, 96, 160, 224)[i % 4])
        n = int((64, 96)[i % 2])
        total += n
        rid = sched.submit(
            [int(x) for x in rng2.randint(1, cfg.vocab_size, size=S)],
            max_new_tokens=n, on_token=mk_on_token(i),
        )
        t_submit[i] = time.perf_counter()
    outs = sched.run()
    dt = time.perf_counter() - t0
    got = sum(len(v) for v in outs.values())
    assert got == total, (got, total)
    ttfts = sorted(t_first[r] - t_submit[r] for r in t_submit)
    out["serving_tok_s_1b"] = round(got / dt, 1)
    out["serving_requests"] = 16
    out["serving_ttft_p50_ms"] = round(ttfts[len(ttfts) // 2] * 1e3, 1)
    out["serving_ttft_p99_ms"] = round(ttfts[-1] * 1e3, 1)
    # the split that says WHERE TTFT went (scheduler-side stamps):
    # queue-wait (submit -> prefill start) vs prefill/compute
    lm = sched.latency_metrics
    out["serving_queue_wait_p50_ms"] = lm["queue_wait_p50_ms"]
    out["serving_queue_wait_p99_ms"] = lm["queue_wait_p99_ms"]
    out["serving_prefill_p50_ms"] = lm["prefill_p50_ms"]
    out["serving_prefill_p99_ms"] = lm["prefill_p99_ms"]
    # the step profiler's attribution block (engine/stepprof.py): the
    # sampled device-drain share of step time and the retrace pressure —
    # trended by scripts/bench_history.py so a regression that turns the
    # step loop host-bound (or shape-polymorphic) is flagged, not argued
    s = prof.summary()
    out["host_stall_frac"] = s["host_stall_frac"]
    out["retraces_per_100_steps"] = s["retraces_per_100_steps"]
    out["stepprof_steps"] = s["steps"]
    out["stepprof_dispatch_total"] = s["dispatch_total"]
    # dispatch economy (docs/tpu_perf_notes.md §dispatch-budget):
    # compiled programs per decoded token and blocking host syncs over
    # the leg — the pair the single-sync speculation work is judged by
    out["dispatches_per_token"] = s["dispatches_per_token"]
    out["stepprof_syncs_total"] = s["syncs_total"]
    if s.get("spec_accept_per_dispatch") is not None:
        out["spec_accept_per_dispatch"] = s["spec_accept_per_dispatch"]


def leg_speculative(out: dict) -> None:
    """Speculation vs plain decode tokens/s, THREE configurations
    (VERDICT r4 missing #1 / next #1 — "a number, not a narrative"):

    * plain decode (the baseline, median-of-3);
    * SELF-draft model speculation at k=4: acceptance ~1 but the draft
      costs as much as the target, so the measured ratio is the fused
      pipeline's overhead ceiling — >= 1x is impossible by construction
      (r4 recorded 0.54x);
    * N-GRAM speculation (the genuinely cheap draft the machinery was
      built for): proposal cost ~zero, so speedup = E[tokens/round] /
      round-overhead.  Swept over k; per-k acceptance and tok/s are
      recorded so the acceptance-vs-speedup relation is a table in the
      JSON, not prose.  Decodes a LONG horizon (256) because the
      repetition n-gram feeds on develops over time."""
    import jax
    import numpy as np

    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.engine.ngram import NgramSpeculator
    from infinistore_tpu.engine.speculative import SpeculativeDecoder
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.llama import init_params, scaled

    cfg = scaled(_bench_model())
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    def eng(n_blocks=256):
        return InferenceEngine(params, cfg, PagedCacheConfig(
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, block_tokens=16, n_blocks=n_blocks,
            dtype="bfloat16",
        ))

    T = 16
    rng = np.random.RandomState(1)
    N = 256

    def preacquire(e, st, total_tokens):
        """Pin the block-table width bucket up front: decode never
        crosses a width bucket mid-run, so each config compiles ONE
        table width instead of three."""
        need = -(-total_tokens // T)
        if need > len(st.block_ids):
            st.block_ids.extend(e.pages.acquire(need - len(st.block_ids)))

    def fresh_prompt():
        return [int(x) for x in rng.randint(1, cfg.vocab_size, size=64)]

    # -- plain baseline over the same long horizon ---------------------
    plain = eng()
    w = plain.prefill(fresh_prompt())
    preacquire(plain, w, 64 + N + 32)
    plain.decode(w, 32)
    plain.decode(w, N)
    plain.release(w)

    def one_plain() -> float:
        st = plain.prefill(fresh_prompt())
        preacquire(plain, st, 64 + N + 32)
        plain.decode(st, 32)
        t0 = time.perf_counter()
        plain.decode(st, N)
        dt = time.perf_counter() - t0
        plain.release(st)
        return N / dt

    plain_tok_s, plain_sp = _median_spread(one_plain, 3)
    out["plain_tok_s"] = round(plain_tok_s, 1)
    out["plain_spread"] = plain_sp

    # -- self-draft model speculation (the pipeline-overhead ceiling) --
    # SAME horizon as the plain baseline: mixing horizons would bias the
    # ratio (context grows with N, so per-token cost does too)
    Nself = N
    warm = SpeculativeDecoder(eng(), eng(), k=4)
    w_t, w_d = warm.prefill(fresh_prompt())
    warm.decode(w_t, w_d, Nself)
    del warm, w_t, w_d  # free both warmup caches before the timed run
    spec = SpeculativeDecoder(eng(), eng(), k=4)

    def one_self() -> float:
        st_t, st_d = spec.prefill(fresh_prompt())
        t0 = time.perf_counter()
        spec.decode(st_t, st_d, Nself)
        dt = time.perf_counter() - t0
        spec.target.release(st_t)
        spec.draft.release(st_d)
        return Nself / dt

    self_tok_s, self_sp = _median_spread(one_self, 3)
    out["spec_tok_s"] = round(self_tok_s, 1)
    out["spec_spread"] = self_sp
    out["spec_speedup"] = round(self_tok_s / plain_tok_s, 2)
    out["spec_acceptance"] = round(spec.acceptance_rate, 3)

    # -- n-gram speculation sweep (the cheap draft) --------------------
    best = 0.0
    for k in (4, 8):
        sp = NgramSpeculator(eng(), k=k, g=2)
        grow = 8 * (k + 1) + 16
        ws = sp.prefill(fresh_prompt())
        preacquire(sp.target, ws, 64 + N + grow)
        sp.decode_batch([ws], N)  # warm both R buckets + shapes
        sp.target.release(ws)

        pairs = []  # (tok_s, acceptance) per repeat, kept TOGETHER

        def one_ng() -> float:
            s2 = NgramSpeculator(sp.target, k=k, g=2)
            st = s2.prefill(fresh_prompt())
            preacquire(s2.target, st, 64 + N + grow)
            t0 = time.perf_counter()
            s2.decode_batch([st], N)
            dt = time.perf_counter() - t0
            pairs.append((N / dt, s2.acceptance_rate))
            s2.target.release(st)
            return N / dt

        tok_s, sp_sp = _median_spread(one_ng, 3)
        # report the MEDIAN RUN's acceptance so the (acceptance, tok/s)
        # pair in the JSON comes from one and the same run
        acc = sorted(pairs)[len(pairs) // 2][1]
        out[f"ngram_spec_k{k}_tok_s"] = round(tok_s, 1)
        out[f"ngram_spec_k{k}_spread"] = sp_sp
        out[f"ngram_spec_k{k}_acceptance"] = round(acc, 3)
        out[f"ngram_spec_k{k}_speedup"] = round(tok_s / plain_tok_s, 2)
        best = max(best, tok_s / plain_tok_s)
    out["ngram_spec_speedup_best"] = round(best, 2)


def leg_prefill_breakdown(out: dict) -> None:
    """Where does a 2k-token prefill's time go?  (VERDICT r4 next #7 —
    the tunnel blocks the real profiler, so attribute by PROXY: time
    each component at the model's exact shapes with the model's own
    weights, compare the sum against the measured whole.)

    * matmul proxy: the L-layer projection/FFN chain (scan over the real
      stacked weights, attention replaced by identity) + lm_head;
    * attention proxy: L causal self-attentions at [1, H, S, D] via the
      same attention entry prefill uses;
    * scatter proxy: the KV page landing (_write_prefill_pages of the
      whole prompt's pages).

    Within-jit fusion means proxies under-count shared overheads, so the
    residual (whole - sum) is reported explicitly as "unaccounted" —
    attribution, not an identity.  Also sweeps prefill_chunk, since the
    chunked path trades attention memory for re-dispatch + prefix-KV
    append costs; the sweep says whether the default chunking is leaving
    MFU on the table."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.attention import causal_attention
    from infinistore_tpu.models.llama import init_params

    cfg = _bench_model()
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    smoke = os.environ.get("ISTPU_BENCH_MODEL") == "tiny"
    S = 256 if smoke else 2048
    rng = np.random.RandomState(0)
    hd = cfg.head_dim

    # -- matmul proxy: projections + FFN + lm_head, attention = identity
    @jax.jit
    def mm_chain(x):  # x [1, S, dim]
        def body(xc, layer):
            q = xc @ layer["wq"]
            k = xc @ layer["wk"]
            v = xc @ layer["wv"]
            del k, v
            att = q.reshape(xc.shape[:-1] + (cfg.n_heads * hd,))
            xc = xc + att @ layer["wo"]
            xc = xc + (
                jax.nn.silu(xc @ layer["w_gate"]) * (xc @ layer["w_up"])
            ) @ layer["w_down"]
            return xc, None

        xc, _ = jax.lax.scan(body, x, params["layers"])
        return (xc @ params["lm_head"]).astype(jnp.bfloat16)

    x0 = jnp.asarray(rng.randn(1, S, cfg.dim), cfg.dtype)

    # chain: feed a cheap slice of the logits back in so repeats can't
    # be memoized
    @jax.jit
    def mm_step(x):
        lg = mm_chain(x)
        return x * 0.999 + 0.001 * (
            lg[..., : cfg.dim].astype(cfg.dtype)
        )

    t_mm = _timeit_chained(lambda x, i: mm_step(x), x0, n=8)

    # -- attention proxy: L causal attentions at the prefill shape
    @jax.jit
    def attn_step(q):
        def body(qc, _):
            # same attention entry AND the same default path prefill
            # uses (flash is opt-in; env controls it here as there)
            o = causal_attention(qc, qc[:, :, : cfg.n_kv_heads],
                                 qc[:, :, : cfg.n_kv_heads],
                                 allow_pallas=True)
            return qc * 0.999 + 0.001 * o, None

        qc, _ = jax.lax.scan(body, q, None, length=cfg.n_layers)
        return qc

    q0 = jnp.asarray(
        rng.randn(1, S, cfg.n_heads, hd), cfg.dtype
    )
    t_attn = _timeit_chained(lambda x, i: attn_step(x), q0, n=8)

    # -- scatter proxy: land the whole prompt's KV pages
    from infinistore_tpu.engine.engine import _write_prefill_pages

    T = 16
    n_pages = S // T
    pc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads, head_dim=hd,
        block_tokens=T, n_blocks=n_pages + 8, dtype="bfloat16",
    )
    from infinistore_tpu.kv.cache import init_cache

    cache0 = init_cache(pc)
    kv = jnp.asarray(
        rng.randn(cfg.n_layers, 2, 1, S, cfg.n_kv_heads, hd), jnp.bfloat16
    )
    ids = jnp.arange(n_pages, dtype=jnp.int32)

    @jax.jit
    def scat_step(cache):
        c2 = _write_prefill_pages(cache, ids, kv, T)
        return c2

    t_scat = _timeit_chained(lambda c, i: scat_step(c), cache0, n=8)

    # -- the measured whole, and the chunk-size sweep
    def ttft_with_chunk(chunk):
        eng = InferenceEngine(params, cfg, PagedCacheConfig(
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads, head_dim=hd,
            block_tokens=T, n_blocks=max(256, 2 * n_pages + 16),
            dtype="bfloat16",
        ), prefill_chunk=chunk)
        w = eng.prefill(
            [int(x) for x in rng.randint(1, cfg.vocab_size, size=S)])
        _fetch(w.last_logits)
        eng.release(w)

        def one() -> float:
            p = [int(x) for x in rng.randint(1, cfg.vocab_size, size=S)]
            t0 = time.perf_counter()
            st = eng.prefill(p)
            _fetch(st.last_logits)
            ms = (time.perf_counter() - t0) * 1e3
            eng.release(st)
            return ms

        med, spread = _median_spread(one, 3)
        return med, spread

    whole_ms, whole_sp = ttft_with_chunk(None)
    out["prefill2k_full_ms"] = round(whole_ms, 1)
    out["prefill2k_full_spread"] = whole_sp
    out["prefill2k_matmul_ms"] = round(t_mm * 1e3, 1)
    out["prefill2k_attention_ms"] = round(t_attn * 1e3, 1)
    out["prefill2k_scatter_ms"] = round(t_scat * 1e3, 1)
    out["prefill2k_unaccounted_ms"] = round(
        whole_ms - (t_mm + t_attn + t_scat) * 1e3, 1
    )
    for chunk in (256, 512):
        if chunk < S:
            ms, sp = ttft_with_chunk(chunk)
            out[f"prefill2k_chunk{chunk}_ms"] = round(ms, 1)
            out[f"prefill2k_chunk{chunk}_spread"] = sp


def leg_distilled_spec(out: dict) -> None:
    """The VERDICT r4 next #1 configuration verbatim: a genuinely cheap
    draft "trained briefly on the target's outputs" vs the 1B target.

    Corpus = the target's own greedy trajectories; the draft distills on
    them (engine/distill.py — sequence-level KD, the standard production
    draft recipe); speculation is then measured on corpus prompts AND
    held-out prompts.  HONESTY NOTE, recorded in the JSON: with a
    RANDOM-INIT target the greedy map is chaotic, so distillation
    memorizes rather than generalizes — corpus-prompt acceptance is the
    in-distribution number (what a real checkpoint's draft would get on
    real text), held-out acceptance collapses toward 0 and is reported
    alongside.  The leg's purpose is the measured end-to-end pipeline at
    realistic acceptance: does a draft at ~3% of target cost with
    acceptance ~0.9 actually beat plain decode on this platform, and by
    how much."""
    import jax
    import numpy as np

    from infinistore_tpu.engine.distill import (
        acceptance_probe,
        distill,
        generate_corpus,
    )
    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.engine.speculative import SpeculativeDecoder
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.llama import init_params, scaled

    cfg = scaled(_bench_model())
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    smoke = os.environ.get("ISTPU_BENCH_MODEL") == "tiny"
    if smoke:
        dcfg = scaled(cfg, n_layers=2, dim=96, ffn_dim=192,
                      n_heads=4, n_kv_heads=2)
        steps, n_seqs, gen = 400, 48, 64  # 1-core CPU: keep the leg short
    else:
        # ~3% of the 1B's per-token matmul cost (the embed/lm_head pair
        # dominates its params but not its FLOPs at B=1)
        dcfg = scaled(cfg, n_layers=2, dim=256, ffn_dim=512,
                      n_heads=4, n_kv_heads=2)
        steps, n_seqs, gen = int(os.environ.get(
            "ISTPU_DISTILL_STEPS", "1500")), 48, 64

    def eng(c, p, n_blocks=256):
        return InferenceEngine(p, c, PagedCacheConfig(
            n_layers=c.n_layers, n_kv_heads=c.n_kv_heads,
            head_dim=c.head_dim, block_tokens=16, n_blocks=n_blocks,
            dtype="bfloat16" if not smoke else c.dtype,
        ))

    target = eng(cfg, params)
    corpus = generate_corpus(target, n_seqs=n_seqs, prompt_len=16,
                             gen_len=gen, batch=8)
    t0 = time.perf_counter()
    dparams, losses = distill(dcfg, corpus, steps=steps, lr=1e-2,
                              batch=32)
    out["distill_steps"] = steps
    out["distill_s"] = round(time.perf_counter() - t0, 1)
    out["distill_final_loss"] = round(losses[-1], 2)

    # acceptance both ways (see docstring)
    in_corpus = [[int(t) for t in corpus[i][:16]] for i in range(4)]
    held_out = [
        [int(x) for x in np.random.RandomState(500 + i).randint(
            1, cfg.vocab_size, size=16)]
        for i in range(4)
    ]
    acc_in, per_round = acceptance_probe(
        eng(cfg, params), eng(dcfg, dparams), in_corpus, gen_len=gen, k=4)
    acc_out, _ = acceptance_probe(
        eng(cfg, params), eng(dcfg, dparams), held_out, gen_len=gen, k=4)
    out["distilled_acceptance_corpus"] = round(acc_in, 3)
    out["distilled_acceptance_heldout"] = round(acc_out, 3)
    out["distilled_tokens_per_round"] = round(per_round, 2)

    # end-to-end: spec tok/s on corpus prompts vs plain decode, SAME
    # horizon, median-of-3 (fresh corpus prompt per repeat)
    N = 128
    plain = eng(cfg, params)
    w = plain.prefill(in_corpus[0])
    plain.decode(w, 32)
    plain.decode(w, N)
    plain.release(w)

    pi = [0]

    def one_plain() -> float:
        # rotate corpus prompts exactly like the spec side below — the
        # two sides must share prompt-sampling methodology
        st = plain.prefill([int(t) for t in corpus[pi[0] % n_seqs][:16]])
        pi[0] += 1
        plain.decode(st, 32)
        t0 = time.perf_counter()
        plain.decode(st, N)
        dt = time.perf_counter() - t0
        plain.release(st)
        return N / dt

    plain_tok_s, _ = _median_spread(one_plain, 3)

    spec = SpeculativeDecoder(eng(cfg, params), eng(dcfg, dparams), k=4)
    w_t, w_d = spec.prefill(in_corpus[1])
    spec.decode(w_t, w_d, N)  # warm every fused shape
    spec.target.release(w_t)
    spec.draft.release(w_d)
    ri = [0]

    def one_spec() -> float:
        p = [int(t) for t in corpus[ri[0] % n_seqs][:16]]
        ri[0] += 1
        st_t, st_d = spec.prefill(p)
        t0 = time.perf_counter()
        spec.decode(st_t, st_d, N)
        dt = time.perf_counter() - t0
        spec.target.release(st_t)
        spec.draft.release(st_d)
        return N / dt

    spec_tok_s, spec_sp = _median_spread(one_spec, 3)
    out["distilled_plain_tok_s"] = round(plain_tok_s, 1)
    out["distilled_spec_tok_s"] = round(spec_tok_s, 1)
    out["distilled_spec_spread"] = spec_sp
    out["distilled_spec_speedup"] = round(spec_tok_s / plain_tok_s, 2)


def leg_invocation_overhead(out: dict) -> None:
    """Quantify the per-``pallas_call`` overhead hypothesis (VERDICT r4
    next #5) with a controlled experiment: the SAME total decode-
    attention work (16 layers, B=8, 1024-token context) compiled as

    * one jit containing 16 single-layer pallas custom calls (the shape
      a real decode step has), vs
    * one jit containing ONE all-layers pallas call
      (``paged_decode_attention_pallas_alllayers`` — identical HBM
      traffic and FLOPs, 1/16th the invocations), vs
    * the XLA gather-then-attend path (the shipping default).

    If the fused call is ~16x cheaper per layer, the overhead theory is
    CONFIRMED and quantified (the difference / 15 is the per-call cost);
    if not, the kernels lose for some other reason and kernel work on
    this platform should stop chasing invocation counts."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from infinistore_tpu.models.attention import paged_decode_attention_xla
    from infinistore_tpu.ops.pallas_attention import (
        paged_decode_attention_pallas,
        paged_decode_attention_pallas_alllayers,
    )

    # CPU smoke runs the kernels in interpret mode (timings meaningless
    # there — the leg exists for the real chip) and at token shapes
    interp = jax.devices()[0].platform != "tpu"
    if interp:
        L, B, H, Hkv, D, T, PAGES = 2, 2, 4, 2, 128, 16, 4
    else:
        L, B, H, Hkv, D, T = 16, 8, 16, 8, 128, 16
        PAGES = 64  # 1024-token context
    rng = np.random.RandomState(0)
    cache = jnp.asarray(
        rng.randn(L, 2, Hkv, PAGES + 1, T, D), jnp.bfloat16
    )
    table = jnp.asarray(
        np.tile(np.arange(1, PAGES + 1, dtype=np.int32), (B, 1))
    )
    lens = jnp.full((B,), PAGES * T, jnp.int32)

    @jax.jit
    def per_layer(qs):
        outs = [
            paged_decode_attention_pallas(
                qs[l], cache[l], table, lens, interpret=interp)
            for l in range(L)
        ]
        o = jnp.stack(outs)
        # chain: next iteration's queries derive from this output, so
        # repeated dispatches can't be memoized
        return qs * 0.999 + 0.001 * o

    @jax.jit
    def fused(qs):
        o = paged_decode_attention_pallas_alllayers(
            qs, cache, table, lens, interpret=interp)
        return qs * 0.999 + 0.001 * o

    @jax.jit
    def xla(qs):
        outs = [
            paged_decode_attention_xla(qs[l], cache[l], table, lens)
            for l in range(L)
        ]
        return qs * 0.999 + 0.001 * jnp.stack(outs)

    qs0 = jnp.asarray(rng.randn(L, B, H, D), jnp.bfloat16)
    t16 = _timeit_chained(lambda x, i: per_layer(x), qs0, n=30)
    t1 = _timeit_chained(lambda x, i: fused(x), qs0, n=30)
    txla = _timeit_chained(lambda x, i: xla(x), qs0, n=30)
    out["invoc_16calls_ms"] = round(t16 * 1e3, 3)
    out["invoc_1call_ms"] = round(t1 * 1e3, 3)
    out["invoc_xla_ms"] = round(txla * 1e3, 3)
    out["invoc_per_call_overhead_ms"] = round(
        (t16 - t1) / (L - 1) * 1e3, 4
    )
    out["invoc_fused_speedup"] = round(t16 / t1, 2)


def _chip_peak_flops_bf16(device_kind: str) -> float:
    """Per-chip peak bf16 FLOPs/s by device kind (public spec sheets); the
    MFU denominator.  Falls back to v5e when the kind is unrecognized."""
    kind = device_kind.lower()
    table = [
        ("v6", 918e12), ("trillium", 918e12),
        ("v5p", 459e12),
        ("v5", 197e12), ("v5e", 197e12), ("v5 lite", 197e12),
        ("v4", 275e12),
        ("v3", 123e12), ("v2", 46e12),
    ]
    for key, peak in table:
        if key in kind:
            return peak
    return 197e12


def _bench_model():
    """LLAMA3_1B for the real run; ISTPU_BENCH_MODEL=tiny swaps in the TINY
    config so the leg code itself can be smoke-tested on CPU."""
    from infinistore_tpu.models.llama import LLAMA3_1B, TINY

    return TINY if os.environ.get("ISTPU_BENCH_MODEL") == "tiny" else LLAMA3_1B


def leg_model_perf(out: dict) -> None:
    """Largest-config-that-fits serving figures (VERDICT r2 next #2):
    LLAMA3_1B bf16 through the engine — TTFT for a 512-token prompt, p50
    per-token decode latency, decode tokens/s at B=1 and B=8, and MFU
    (model matmul FLOPs/token x tok/s / chip peak bf16 FLOPs/s)."""
    import jax
    import numpy as np

    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.llama import init_params

    cfg = _bench_model()
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    epc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        block_tokens=16, n_blocks=512, dtype="bfloat16",
    )
    eng = InferenceEngine(params, cfg, epc)

    S = 512
    rng = np.random.RandomState(0)
    prompt = [int(x) for x in rng.randint(1, cfg.vocab_size, size=S)]
    # a DIFFERENT same-length prompt for the measured run: re-prefilling the
    # warmup prompt would hit the prefix cache and take a different shape
    # path (16-token tail + bucketed prefix buffer) whose fresh XLA compile
    # is what the old version of this leg reported as "TTFT"
    prompt2 = [int(x) for x in rng.randint(1, cfg.vocab_size, size=S)]

    # TTFT: prompt ingestion + the ACTUAL first token on the host,
    # post-compile wall time.  _fetch, not block_until_ready: the runtime
    # reports readiness optimistically (measured 6 ms "ready" vs 87 ms to
    # produce the logits)
    st = eng.prefill(prompt)  # compile the no-reuse 512-token path
    _fetch(st.last_logits)
    eng.release(st)

    def one_ttft() -> float:
        p = [int(x) for x in rng.randint(1, cfg.vocab_size, size=S)]
        t0 = time.perf_counter()
        s = eng.prefill(p)  # same shapes, no prefix hit -> pure execution
        _fetch(s.last_logits)
        ms = (time.perf_counter() - t0) * 1e3
        eng.release(s)
        return ms

    ttft_med, ttft_sp = _median_spread(one_ttft, 3)
    out["ttft_ms_1b_512"] = round(ttft_med, 1)
    out["ttft_1b_512_spread"] = ttft_sp
    st = eng.prefill(prompt2)  # the state the decode legs below use

    # matmul FLOPs/token: 2 x non-embedding params + attention scores/values
    # (4 x n_layers x ctx x head_dim x n_heads) at the bench's mean context
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(params)
    )
    n_embed = cfg.vocab_size * cfg.dim
    ctx = S + 64
    flops_tok = 2 * (n_params - n_embed) + (
        4 * cfg.n_layers * ctx * cfg.head_dim * cfg.n_heads
    )
    peak = _chip_peak_flops_bf16(jax.devices()[0].device_kind)
    out["chip_peak_bf16_tflops"] = round(peak / 1e12, 1)

    # B=1 decode: p50 per-token latency + tokens/s
    eng.decode(st, eng.decode_chunk)  # compile the scan
    lats = []
    for _ in range(4):
        t0 = time.perf_counter()
        eng.decode(st, eng.decode_chunk)
        lats.append((time.perf_counter() - t0) / eng.decode_chunk)
    lats.sort()
    p50 = lats[len(lats) // 2]
    out["decode_p50_token_ms_1b"] = round(p50 * 1e3, 2)
    out["decode_tok_s_1b_b1"] = round(1.0 / p50, 1)
    out["mfu_1b_b1"] = round(flops_tok / p50 / peak, 4)
    eng.release(st)

    # B=8 lockstep decode: throughput + MFU (the serving configuration).
    # Warm a full-length throwaway run first: the block table widens in
    # pow2 buckets as sequences grow, and a width bucket first crossed
    # inside the timed region would bill an XLA compile as decode time.
    B = 8
    n = eng.decode_chunk * 4
    warm_sts = [eng.prefill(prompt[:64]) for _ in range(B)]
    eng.decode_batch(warm_sts, eng.decode_chunk)
    eng.decode_batch(warm_sts, n)
    for s in warm_sts:
        eng.release(s)
    def one_b8() -> float:
        states = [eng.prefill(prompt[:64]) for _ in range(B)]
        eng.decode_batch(states, eng.decode_chunk)  # warmed widths
        t0 = time.perf_counter()
        eng.decode_batch(states, n)
        dt = time.perf_counter() - t0
        for s in states:
            eng.release(s)
        return B * n / dt

    tok_s, b8_sp = _median_spread(one_b8, 3)
    out["decode_tok_s_1b_b8"] = round(tok_s, 1)
    out["decode_1b_b8_spread"] = b8_sp
    ctx8 = 64 + n
    flops_tok8 = 2 * (n_params - n_embed) + (
        4 * cfg.n_layers * ctx8 * cfg.head_dim * cfg.n_heads
    )
    out["mfu_1b_b8"] = round(flops_tok8 * tok_s / peak, 4)


def leg_prefill_stream(out: dict) -> None:
    """Store-attached vs detached prefill wall time (VERDICT r2 missing #2:
    the reference streams KV layer-by-layer during prefill at <= 1%
    overhead; ours streams per chunk through a background pusher).  Ratio
    ~1.0 = the store hop is fully hidden behind compute."""
    import jax
    import numpy as np

    from infinistore_tpu import ClientConfig, InfinityConnection
    from infinistore_tpu.config import TYPE_SHM
    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.llama import init_params

    cfg = _bench_model()
    params = init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    epc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        block_tokens=16, n_blocks=512, dtype="bfloat16",
    )
    S, C = 1024, 256  # chunked prefill: 4 chunks, 3 of them streamed
    rng = np.random.RandomState(0)

    def run(conn, quant=None, durability="strict", tag=""):
        """Median-of-3 prefill wall seconds (+ rel spread, + median
        post-return drain seconds under relaxed durability, + the last
        push's per-stage breakdown).  Fresh prompts per repeat; one
        warmup prefill for compiles."""
        eng = InferenceEngine(
            params, cfg, epc, conn=conn,
            model_id=f"bench-{id(conn)}-{quant}-{tag}",
            prefill_chunk=C, kv_quant=quant, store_durability=durability,
        )
        prompt = [int(x) for x in rng.randint(1, cfg.vocab_size, size=S)]
        st = eng.prefill(prompt)  # compile
        _fetch(st.last_logits)
        eng.store_flush()
        eng.release(st)
        drains = []

        def one() -> float:
            p2 = [int(x) for x in rng.randint(1, cfg.vocab_size, size=S)]
            t0 = time.perf_counter()
            st = eng.prefill(p2)
            _fetch(st.last_logits)  # ground-truth completion, see _fetch
            dt = time.perf_counter() - t0
            t1 = time.perf_counter()
            eng.store_flush()  # relaxed: the pushes still draining
            drains.append(time.perf_counter() - t1)
            eng.release(st)
            return dt

        med, spread = _median_spread(one, 3)
        drains.sort()
        stages = (getattr(eng.transfer, "last_push_stages", {}) or {}
                  if eng.transfer is not None else {})
        return med, spread, drains[len(drains) // 2], stages

    t_detached, sp_detached, _, _ = run(None)

    service, manage = _free_port(), _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "infinistore_tpu.server",
            "--service-port", str(service), "--manage-port", str(manage),
            "--prealloc-size", "2", "--minimal-allocate-size", "64",
            "--log-level", "warning", "--auto-increase",
            # python backend: the one that negotiates integrity AND
            # alloc-first zero-copy pushes (see leg_store_hop)
            "--backend", "python",
        ],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", service), timeout=1).close()
                break
            except OSError:
                time.sleep(0.2)
        conn = InfinityConnection(ClientConfig(
            host_addr="127.0.0.1", service_port=service,
            connection_type=TYPE_SHM,
            # python client: the alloc-first/integrity data plane (see
            # leg_store_hop), with a bounded per-op deadline
            op_timeout_s=60.0,
        ))
        conn.connect()
        t_bf16, sp_bf16, _, _ = run(conn, quant=None, tag="bf16")
        # int8 page quantization halves the D2H + pool bytes; on transfer-
        # bound links (this tunnel: ~16 MB/s D2H) the saving shows directly
        t_q8, sp_q8, _, _ = run(conn, quant="int8", tag="q8s")
        # the SHIPPING default: int8 + relaxed durability — prefill
        # returns when the last chunk's pages are queued; the flush
        # rides behind decode.  drain = how long the queue takes to
        # land after return (the bandwidth half of the old 10x).
        t_rel, sp_rel, t_drain, push_stages = run(
            conn, quant="int8", durability="relaxed", tag="q8r"
        )
        conn.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)

    out["prefill_ms_detached"] = round(t_detached * 1e3, 1)
    out["prefill_detached_spread"] = sp_detached
    out["prefill_ms_store_attached_bf16_strict"] = round(t_bf16 * 1e3, 1)
    out["prefill_bf16_strict_spread"] = sp_bf16
    out["prefill_ms_store_attached_q8_strict"] = round(t_q8 * 1e3, 1)
    out["prefill_q8_strict_spread"] = sp_q8
    out["prefill_ms_store_attached"] = round(t_rel * 1e3, 1)  # the default
    out["prefill_relaxed_spread"] = sp_rel
    out["prefill_store_drain_ms"] = round(t_drain * 1e3, 1)
    # where the default config's push time goes (last chunk's push, per
    # stage) — the same attribution key as leg_store_hop's breakdown
    for k in ("d2h_s", "pool_copy_s", "alloc_s", "commit_s", "wire_s"):
        if push_stages.get(k):
            out[f"prefill_push_{k}"] = round(push_stages[k], 4)
    out["prefill_push_zero_copy_bands"] = push_stages.get(
        "zero_copy_bands", 0)
    # headline: the DEFAULT configuration's overhead (VERDICT r4 next #2
    # target: < 2x on chip)
    out["prefill_store_overhead"] = round(t_rel / t_detached, 3)
    out["prefill_store_overhead_strict_q8"] = round(t_q8 / t_detached, 3)
    # barrier-vs-bandwidth split of the strict overhead: the share of
    # (strict - detached) that the relaxed mode removes is the
    # durability BARRIER; the rest is D2H/pool bandwidth the prefill
    # still can't hide
    extra = t_q8 - t_detached
    if extra > 1e-9:
        # clamped to [0, 1]: medians of separate runs can cross on a
        # noisy tunnel, and a share above 1 is not a meaningful fraction
        out["prefill_store_barrier_share"] = round(
            min(1.0, max(0.0, (t_q8 - t_rel)) / extra), 3
        )


def leg_mosaic_tests(out: dict) -> None:
    """Fold the TPU-gated Mosaic acceptance tests into the bench attempt
    (VERDICT r3 next #1): the kernels' real-compile path rides along the
    moment hardware answers, instead of waiting for someone to remember
    ``ISTPU_TEST_TPU=1 pytest -k on_tpu``.  Runs pytest IN-PROCESS so the
    tests reuse this process's already-initialized TPU client — a second
    PJRT client from a subprocess can deadlock on chip exclusivity.
    Ordered last in the leg list: in-process pytest imports the test
    modules into this interpreter, which must not perturb earlier legs."""
    import pytest

    fails: list = []
    counts = {"passed": 0, "failed": 0, "skipped": 0}

    class _Count:
        def pytest_runtest_logreport(self, report):
            if report.when == "call":
                if report.passed:
                    counts["passed"] += 1
                elif report.failed:
                    counts["failed"] += 1
                    fails.append(
                        f"{report.nodeid}: {report.longreprtext[-400:]}"
                    )
                elif report.skipped:  # pytest.skip() inside the test body
                    counts["skipped"] += 1
            elif report.when == "setup":
                if report.skipped:
                    counts["skipped"] += 1
                elif report.failed:
                    counts["failed"] += 1
                    fails.append(
                        f"{report.nodeid}: {report.longreprtext[-400:]}"
                    )

    os.environ["ISTPU_TEST_TPU"] = "1"
    repo = os.path.dirname(os.path.abspath(__file__))
    pytest.main(
        [os.path.join(repo, "tests", "test_ops.py"), "-k", "on_tpu",
         "-q", "--no-header", "-p", "no:cacheprovider"],
        plugins=[_Count()],
    )
    out["mosaic_tests_passed"] = counts["passed"]
    if counts["skipped"]:
        out["mosaic_tests_skipped"] = counts["skipped"]
    if counts["failed"]:
        out["mosaic_tests_failed"] = counts["failed"]
        out["mosaic_tests_tail"] = " || ".join(fails)[:1500]


def _relay_diag() -> dict:
    """Instant, jax-free picture of the tunnel relay this PJRT plugin dials:
    which loopback ports listen / accept.  When init later hangs, this
    pins the failure to a layer — no listener (relay down) vs. connect OK
    but claim never answered (wedged upstream of the relay), the round-3/4
    failure mode."""
    diag: dict = {}
    listeners = []
    try:
        with open("/proc/net/tcp") as f:
            for line in f.readlines()[1:]:
                parts = line.split()
                local, state = parts[1], parts[3]
                if state == "0A":  # LISTEN
                    ip, port = local.split(":")
                    if ip in ("00000000", "0100007F"):
                        listeners.append(int(port, 16))
        diag["loopback_listeners"] = sorted(set(listeners))
    except OSError as e:
        diag["loopback_listeners_error"] = repr(e)
    for port in (8082, 8083):  # axon stateful/stateless service ports
        s = socket.socket()
        s.settimeout(2)
        try:
            s.connect(("127.0.0.1", port))
            diag[f"port_{port}"] = "open"
        except OSError as e:
            diag[f"port_{port}"] = f"closed ({e.strerror or e})"
        finally:
            s.close()
    return diag


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser("bench_tpu.py")
    ap.add_argument("--trace-out", default=None, metavar="FILE",
                    help="write ONE merged Perfetto-loadable Chrome "
                         "trace of the whole run: every leg wrapped in "
                         "a bench.<leg> trace, with the engine spans, "
                         "store-hop spans, and the step profiler's "
                         "device sub-track inside (replaces the old "
                         "bare jax.profiler directory — use "
                         "utils.profiling.device_trace for an xprof "
                         "capture)")
    args = ap.parse_args()

    # Staged init (VERDICT r3 next #1): every step updates ``diag["phase"]``
    # so when a wedged tunnel hangs PJRT client creation (round-2/3/4
    # failure mode) the watchdog emits a STRUCTURED record naming exactly
    # how far init got, plus the relay socket picture and the hung thread's
    # Python stack (faulthandler -> stderr, which bench.py folds into the
    # final JSON) — instead of one warning line.
    import faulthandler
    import threading

    init_done = threading.Event()
    diag: dict = {"phase": "start"}

    def set_phase(p: str) -> None:
        diag["phase"] = p
        diag["phase_t"] = round(time.perf_counter() - t0, 1)
        print(f"# bench_tpu phase: {p}", file=sys.stderr, flush=True)

    t0 = time.perf_counter()
    init_timeout = float(os.environ.get("ISTPU_TPU_INIT_TIMEOUT", "150"))

    def watchdog():
        if not init_done.wait(init_timeout):
            print(json.dumps({"error": "tpu init hang",
                              "init_phase_reached": diag.get("phase"),
                              "init_phase_entered_at_s": diag.get("phase_t"),
                              **{k: v for k, v in diag.items()
                                 if k not in ("phase", "phase_t")}}),
                  flush=True)
            os._exit(1)

    threading.Thread(target=watchdog, daemon=True).start()
    # snapshot the hung stack ~10 s before the watchdog fires, so the record
    # shows WHERE inside the plugin init sat (make_c_api_client etc.)
    faulthandler.dump_traceback_later(max(init_timeout - 10, 5), exit=False)

    set_phase("relay_probe")
    diag["relay"] = _relay_diag()

    set_phase("jax_import")
    import jax

    # honor an explicit JAX_PLATFORMS even where a platform plugin pinned
    # jax_platforms at interpreter start (same rule as tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    set_phase("backend_init")
    platform = jax.devices()[0].platform
    diag["device_kind"] = jax.devices()[0].device_kind

    set_phase("first_dispatch")
    import jax.numpy as jnp

    jnp.add(jnp.ones((8,)), 1.0).block_until_ready()

    set_phase("first_compile")
    jax.jit(lambda x: x * 2.0 + 1.0)(jnp.ones((128, 128))).block_until_ready()

    init_done.set()
    faulthandler.cancel_dump_traceback_later()
    set_phase("legs")
    if platform != "tpu" and os.environ.get("ISTPU_TPU_FORCE") != "1":
        # ISTPU_TPU_FORCE=1 runs the legs on whatever backend is present
        # (CPU smoke-testing of the leg code itself)
        print(json.dumps({"error": "no tpu", "platform": platform,
                          "relay": diag.get("relay")}))
        return 1

    # Internal deadline: bench.py SIGKILLs this leg at its own timeout, which
    # would lose EVERY number; instead stop starting new legs in time to
    # print what we have.  Legs are ordered serving-path-first so a slow
    # tunnel still yields the headline HBM<->store and kernel figures.
    # raised from 720 with the median-of-3 instrumentation (every timed
    # leg now costs ~3x) — bench.py's subprocess timeout tracks this
    budget = float(os.environ.get("ISTPU_TPU_LEG_BUDGET", "1500"))
    t_start = time.perf_counter()

    out: dict = {"device_kind": diag.get("device_kind", "")}
    legs = [
        # compute-perf legs FIRST: the transfer-heavy store legs leave the
        # tunneled runtime's queue warm with bulk work, which inflates the
        # next leg's sync waits (measured: TTFT 6 ms clean vs 86 ms when
        # run after store_hop)
        ("model_perf", leg_model_perf),
        ("engine", leg_engine),
        ("serving", leg_serving),
        ("speculative", leg_speculative),
        ("distilled_spec", leg_distilled_spec),
        ("decode_kernel", leg_decode_kernel),
        ("invocation_overhead", leg_invocation_overhead),
        ("prefill_breakdown", leg_prefill_breakdown),
        ("flash_kernel", leg_flash_kernel),
        ("store_hop", leg_store_hop),
        ("prefill_stream", leg_prefill_stream),
        # real chip only (ISTPU_TEST_TPU=1 un-pins the test conftest's CPU
        # platform, so a CPU smoke run would re-enter the wedged-tunnel
        # init), and LAST (in-process pytest imports test modules)
        *([("mosaic_tests", leg_mosaic_tests)] if platform == "tpu" else []),
    ]
    from infinistore_tpu.utils import tracing as _tracing

    for name, leg in legs:
        if time.perf_counter() - t_start > budget:
            out[f"{name}_skipped"] = "leg budget exhausted"
            continue
        set_phase(f"leg:{name}")
        t_leg = time.perf_counter()
        try:
            # one trace per leg: the engine/store spans (and the step
            # profiler's device sub-track) nest under bench.<leg>, so
            # --trace-out yields one merged Perfetto file for the run
            with _tracing.trace(f"bench.{name}"):
                leg(out)
            out[f"{name}_s"] = round(time.perf_counter() - t_leg, 1)
        except Exception as e:  # noqa: BLE001 - one leg must not sink the rest
            out[f"{name}_error"] = repr(e)[:200]
        # cumulative snapshot: if the caller must SIGKILL us mid-leg it can
        # still salvage every completed leg from the last stdout line
        print(json.dumps(out), flush=True)

    # staged on-chip acceptance asserts (ROADMAP item 2): evaluated
    # ONLY when this run executed on a real chip — the committed
    # snapshot rides bench.py marked ``tpu_stale`` and a stale copy of
    # an old number must never masquerade as a fresh pass/fail.  A miss
    # is recorded in the JSON (and on stderr) instead of a hard exit:
    # bench.py treats a non-zero rc as "no TPU leg" and would discard
    # every number alongside the verdict.
    if platform == "tpu":
        floors = {"spec_speedup": 1.3, "pallas_speedup_vs_xla": 1.0}
        checks = {
            key: {"value": out[key], "floor": floor,
                  "ok": out[key] >= floor}
            for key, floor in floors.items()
            if isinstance(out.get(key), (int, float))
        }
        if checks:
            out["onchip_asserts"] = checks
            failures = sorted(
                key for key, c in checks.items() if not c["ok"])
            if failures:
                out["onchip_assert_failures"] = failures
                print(f"# ON-CHIP ASSERTS FAILED: {failures} "
                      f"(floors: {floors})", file=sys.stderr)

    # final line includes any *_skipped markers written on the continue path
    print(json.dumps(out), flush=True)

    if args.trace_out:
        # the merged Perfetto export of the whole run (bench.<leg> roots
        # with every nested engine/store/device span) — the --trace-out
        # contract used to hand back a raw jax.profiler directory only
        # TensorBoard could open; this file loads at ui.perfetto.dev
        try:
            with open(args.trace_out, "w") as f:
                f.write(_tracing.TRACER.export_chrome_json())
            print(f"# merged Perfetto trace written to {args.trace_out}",
                  file=sys.stderr)
        except OSError as e:
            print(f"# trace-out failed: {e}", file=sys.stderr)

    # refresh the committed stale-fallback snapshot whenever a real-chip
    # run completes (the tunnel can wedge for hours — capture evidence
    # the moment it answers; bench.py merges this file marked stale if
    # the tunnel is dead at bench time)
    # success gate: a degraded run (tunnel wedged mid-run -> all legs
    # errored/skipped) must NOT clobber the committed good capture that
    # bench.py falls back on — that fallback exists precisely for the
    # degraded case
    ok_legs = sum(1 for name, _ in legs if f"{name}_s" in out)
    bad_legs = sum(
        1 for name, _ in legs
        if f"{name}_error" in out or f"{name}_skipped" in out
    )
    healthy = ok_legs >= 5 and ok_legs > bad_legs
    if (platform == "tpu" and healthy
            and os.environ.get("ISTPU_WRITE_SNAPSHOT", "1") != "0"):
        snap = {
            "captured_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "note": "real-chip bench_tpu.py output (ground-truth "
                    "timing); auto-refreshed on successful runs",
            **out,
        }
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_TPU_SNAPSHOT.json")
        try:
            with open(path, "w") as f:
                json.dump(snap, f, indent=1)
            print(f"# snapshot refreshed: {path}", file=sys.stderr)
        except OSError as e:
            print(f"# snapshot refresh failed: {e}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
