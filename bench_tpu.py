"""TPU-in-the-loop benchmark leg (run by bench.py in a subprocess).

Measures the paths the host-only bench can't (VERDICT round-1 weak #2/#4/#5):

1. the full serving hop between TPU HBM and the store —
   paged-cache -> fused gather -> D2H -> zero-copy put (``save_pages``) and
   get -> H2D -> fused scatter (``load_pages``) — against a live server
   (reference analog: benchmark.py src/dst cuda device selection,
   reference infinistore/benchmark.py:144-247);
2. the Pallas paged-decode attention kernel and the flash prefill kernel vs
   their XLA paths on the real chip (compile acceptance + us/step +
   effective HBM GB/s);
3. end-to-end decode tokens/s for the TINY model through the engine's
   compiled scan loop.

Each leg runs independently: a kernel Mosaic rejection or a store hiccup is
recorded as ``<leg>_error`` in the JSON instead of sinking the other
numbers.  Prints ONE JSON line; exits non-zero only if no TPU is reachable.
bench.py treats failure/timeout as "no TPU leg" and reports host metrics
only, so a wedged TPU tunnel can never hang the driver bench.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _timeit(fn, n=100, budget_s: float = 10.0):
    """Mean seconds/call; ``n`` shrinks so the loop fits ``budget_s`` (tunnel
    dispatch latency varies wildly between environments)."""
    fn().block_until_ready()
    t0 = time.perf_counter()
    fn().block_until_ready()
    once = time.perf_counter() - t0
    n = max(3, min(n, int(budget_s / max(once, 1e-6))))
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    r.block_until_ready()
    return (time.perf_counter() - t0) / n


def leg_decode_kernel(out: dict) -> None:
    """Pallas paged-decode attention vs XLA gather path on chip."""
    import jax.numpy as jnp
    import numpy as np

    from infinistore_tpu.models.attention import paged_decode_attention_xla
    from infinistore_tpu.ops import paged_decode_attention_pallas

    B, H, Hkv, D, T = 4, 32, 8, 128, 16
    n_blocks, max_pages = 512, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, D), dtype=jnp.bfloat16)
    cache_l = jnp.asarray(
        rng.randn(2, Hkv, n_blocks, T, D) * 0.1, dtype=jnp.bfloat16
    )
    table = jnp.asarray(
        rng.randint(0, n_blocks, size=(B, max_pages)), dtype=jnp.int32
    )
    lens = jnp.asarray([1000, 517, 64, 3], dtype=jnp.int32)

    o_p = paged_decode_attention_pallas(q, cache_l, table, lens).block_until_ready()
    o_x = paged_decode_attention_xla(q, cache_l, table, lens).block_until_ready()
    err = float(jnp.max(jnp.abs(o_p.astype(jnp.float32) - o_x.astype(jnp.float32))))
    out["pallas_max_abs_err"] = round(err, 4)

    tp = _timeit(lambda: paged_decode_attention_pallas(q, cache_l, table, lens))
    tx = _timeit(lambda: paged_decode_attention_xla(q, cache_l, table, lens))
    kv_bytes = B * max_pages * 2 * Hkv * T * D * 2  # pages each query touches
    out["pallas_us"] = round(tp * 1e6, 1)
    out["xla_us"] = round(tx * 1e6, 1)
    out["pallas_speedup_vs_xla"] = round(tx / tp, 2)
    out["pallas_hbm_gbps"] = round(kv_bytes / tp / 1e9, 1)


def leg_flash_kernel(out: dict) -> None:
    """Flash prefill attention vs XLA SDPA (Llama-8B head shapes, 2k ctx)."""
    import jax.numpy as jnp
    import numpy as np

    from infinistore_tpu.models.attention import causal_attention
    from infinistore_tpu.ops import flash_causal_attention_pallas

    rng = np.random.RandomState(1)
    S = 2048
    fq = jnp.asarray(rng.randn(1, S, 32, 128) * 0.1, dtype=jnp.bfloat16)
    fk = jnp.asarray(rng.randn(1, S, 8, 128) * 0.1, dtype=jnp.bfloat16)
    fv = jnp.asarray(rng.randn(1, S, 8, 128) * 0.1, dtype=jnp.bfloat16)
    of = flash_causal_attention_pallas(fq, fk, fv).block_until_ready()
    ox = causal_attention(fq, fk, fv).block_until_ready()
    out["flash_max_abs_err"] = round(
        float(jnp.max(jnp.abs(of.astype(jnp.float32) - ox.astype(jnp.float32)))), 4
    )
    tf = _timeit(lambda: flash_causal_attention_pallas(fq, fk, fv), n=20)
    txp = _timeit(lambda: causal_attention(fq, fk, fv), n=20)
    out["flash_prefill_us"] = round(tf * 1e6, 1)
    out["xla_prefill_us"] = round(txp * 1e6, 1)
    out["flash_speedup_vs_xla"] = round(txp / tf, 2)


def leg_store_hop(out: dict) -> None:
    """HBM <-> store bandwidth through a live server (Llama-3-8B KV shapes,
    SURVEY §6 config 2; 64 KiB/page/layer, 128 MiB per round)."""
    import jax.numpy as jnp

    from infinistore_tpu import ClientConfig, InfinityConnection
    from infinistore_tpu.config import TYPE_SHM
    from infinistore_tpu.kv.cache import PagedCacheConfig, init_cache
    from infinistore_tpu.kv.transfer import KVTransferEngine

    pc = PagedCacheConfig(
        n_layers=32, n_kv_heads=8, head_dim=128, block_tokens=16,
        n_blocks=128, dtype="bfloat16",
    )
    service, manage = _free_port(), _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "infinistore_tpu.server",
            "--service-port", str(service), "--manage-port", str(manage),
            "--prealloc-size", "2", "--minimal-allocate-size", "64",
            "--log-level", "warning", "--auto-increase",
        ],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", service), timeout=1).close()
                break
            except OSError:
                time.sleep(0.2)

        conn = InfinityConnection(ClientConfig(
            host_addr="127.0.0.1", service_port=service, connection_type=TYPE_SHM,
        ))
        conn.connect()
        eng = KVTransferEngine(conn, pc)
        cache = init_cache(pc)
        cache = cache + jnp.asarray(0.125, dtype=cache.dtype)  # touch HBM
        cache.block_until_ready()

        n_chunks = 64
        chunk_bytes = pc.page_bytes * pc.n_layers * n_chunks  # 128 MiB
        ids = list(range(n_chunks))

        def put(tag):
            ks = [f"bench-{tag}-{i}" for i in range(n_chunks)]
            t0 = time.perf_counter()
            eng.save_pages(cache, ids, ks)
            return time.perf_counter() - t0, ks

        put("warm")  # compile the gather + first registration
        t_put, keys = put("r0")
        t2, _ = put("r1")
        t_put = min(t_put, t2)

        def get(ks):
            t0 = time.perf_counter()
            c2 = eng.load_pages(cache, ids, ks)
            c2.block_until_ready()
            return time.perf_counter() - t0

        get(keys)  # compile the scatter
        t_get = min(get(keys), get(keys))

        out["hbm_put_gbps"] = round(chunk_bytes / t_put / 1e9, 2)
        out["hbm_get_gbps"] = round(chunk_bytes / t_get / 1e9, 2)
        conn.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def leg_engine(out: dict) -> None:
    """End-to-end decode tokens/s (TINY) through the compiled scan loop."""
    import jax
    import numpy as np

    from infinistore_tpu.engine.engine import InferenceEngine
    from infinistore_tpu.kv.cache import PagedCacheConfig
    from infinistore_tpu.models.llama import TINY, init_params

    cfg = TINY
    params = init_params(cfg, jax.random.PRNGKey(0))
    epc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        block_tokens=16, n_blocks=64, dtype="bfloat16",
    )
    eng = InferenceEngine(params, cfg, epc)
    prompt = [int(x) for x in np.arange(1, 33)]
    st = eng.prefill(prompt)
    eng.decode(st, 64)  # compile both chunk sizes
    t0 = time.perf_counter()
    eng.decode(st, 128)
    dt = time.perf_counter() - t0
    out["decode_tok_s_tiny"] = round(128 / dt, 1)


def main() -> int:
    import jax

    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"error": "no tpu"}))
        return 1

    # Internal deadline: bench.py SIGKILLs this leg at its own timeout, which
    # would lose EVERY number; instead stop starting new legs in time to
    # print what we have.  Legs are ordered serving-path-first so a slow
    # tunnel still yields the headline HBM<->store and kernel figures.
    budget = float(os.environ.get("ISTPU_TPU_LEG_BUDGET", "480"))
    t_start = time.perf_counter()

    out: dict = {}
    for name, leg in [
        ("store_hop", leg_store_hop),
        ("decode_kernel", leg_decode_kernel),
        ("engine", leg_engine),
        ("flash_kernel", leg_flash_kernel),
    ]:
        if time.perf_counter() - t_start > budget:
            out[f"{name}_skipped"] = "leg budget exhausted"
            continue
        try:
            leg(out)
        except Exception as e:  # noqa: BLE001 - one leg must not sink the rest
            out[f"{name}_error"] = repr(e)[:200]
        # cumulative snapshot: if the caller must SIGKILL us mid-leg it can
        # still salvage every completed leg from the last stdout line
        print(json.dumps(out), flush=True)

    # final line includes any *_skipped markers written on the continue path
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
