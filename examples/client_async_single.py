"""Async client example: single awaited transfer + existence probes.

Reference parity: infinistore/example/client_async_single.py.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import asyncio
import uuid

import numpy as np

import infinistore_tpu as ist


async def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1")
    ap.add_argument("--service-port", type=int, default=22345)
    args = ap.parse_args()

    conn = ist.InfinityConnection(
        ist.ClientConfig(
            host_addr=args.server,
            service_port=args.service_port,
            connection_type=ist.TYPE_SHM,
        )
    )
    await conn.connect_async()

    key = f"single-{uuid.uuid4().hex[:8]}"
    src = np.arange(64 * 1024, dtype=np.uint8)
    conn.register_mr(src)
    await conn.write_cache_async([(key, 0)], src.nbytes, src.ctypes.data)
    print("exists after write:", conn.check_exist(key))

    dst = np.zeros_like(src)
    conn.register_mr(dst)
    await conn.read_cache_async([(key, 0)], dst.nbytes, dst.ctypes.data)
    assert np.array_equal(src, dst)
    print("single async round-trip OK")
    conn.close()


if __name__ == "__main__":
    asyncio.run(main())
