"""Continuous-batching serving with store-backed prefix reuse.

Boots an engine on the TINY Llama config (swap in models/hf.py
``params_from_hf`` + a real checkpoint for production shapes), submits a mix
of greedy and sampled requests to the scheduler, and — when a store server
is reachable — shows a second engine reusing the first one's prefilled KV
through the store (the reference's LMCache prefix-reuse deployment,
reference docs/source/design.rst).

Usage:
    python examples/serving.py [--service-port 22345]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

# honor JAX_PLATFORMS even where a platform plugin pinned the backend at
# interpreter start (same workaround as tests/conftest.py)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import infinistore_tpu as ist
from infinistore_tpu.engine import InferenceEngine, Scheduler
from infinistore_tpu.kv import PagedCacheConfig
from infinistore_tpu.models import TINY, init_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--service-port", type=int, default=0,
                    help="store server data port (0 = run without a store)")
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args()

    conn = None
    if args.service_port:
        conn = ist.InfinityConnection(ist.ClientConfig(
            host_addr=args.host, service_port=args.service_port,
            connection_type=ist.TYPE_SHM))
        conn.connect()

    cfg = TINY
    params = init_params(cfg, jax.random.PRNGKey(0))
    pc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, block_tokens=16, n_blocks=256,
    )
    engine = InferenceEngine(params, cfg, pc, conn=conn, prefill_chunk=64)
    sched = Scheduler(engine, max_batch=4)

    prompts = {
        "a": list(range(1, 40)),
        "b": list(range(1, 12)),
        "c": [7, 99, 404, 42],
    }
    ids = {}
    for name, p in prompts.items():
        ids[name] = sched.submit(p, 32)
    ids["sampled"] = sched.submit(
        prompts["a"], 32, sample="categorical", temperature=0.8, top_k=40)
    # streamed request: tokens arrive at every decode-chunk boundary
    streamed: list = []
    ids["streamed"] = sched.submit(
        prompts["b"], 16,
        on_token=lambda toks, done: streamed.append((len(toks), done)))

    t0 = time.time()
    out = sched.run()
    dt = time.time() - t0
    n_tok = sum(len(v) for v in out.values())
    print(f"{len(out)} requests, {n_tok} tokens in {dt:.2f}s "
          f"({n_tok / dt:.1f} tok/s aggregate)")
    for name, rid in ids.items():
        print(f"  {name:8s} -> {out[rid][:8]}...")
    print(f"  streamed deliveries (n_tokens, done): {streamed}")

    if conn is not None:
        eng2 = InferenceEngine(params, cfg, pc, conn=conn)
        st = eng2.prefill(prompts["a"])
        print(f"second engine reused {st.reused_chunks} stored chunks "
              f"of prompt 'a' from the store")
    return 0


if __name__ == "__main__":
    sys.exit(main())
