"""TCP inline client example (the cross-host / DCN path).

Single-key tcp_write_cache / tcp_read_cache, as in the reference's
infinistore/example/tcp_client.py.  Works against a server on another host.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import uuid

import numpy as np

import infinistore_tpu as ist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1")
    ap.add_argument("--service-port", type=int, default=22345)
    args = ap.parse_args()

    conn = ist.InfinityConnection(
        ist.ClientConfig(
            host_addr=args.server,
            service_port=args.service_port,
            connection_type=ist.TYPE_TCP,
        )
    )
    conn.connect()

    key = f"tcp-{uuid.uuid4().hex[:8]}"
    src = np.random.randint(0, 256, size=1 << 20, dtype=np.uint8)
    conn.tcp_write_cache(key, src.ctypes.data, src.nbytes)
    out = conn.tcp_read_cache(key)
    assert np.array_equal(out, src)
    print("tcp round-trip OK;", "exists:", conn.check_exist(key))
    conn.delete_keys([key])
    conn.close()


if __name__ == "__main__":
    main()
