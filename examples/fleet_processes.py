"""Subprocess fleet bring-up: the production shape of PD disaggregation.

Boots FOUR separate OS processes — a store node, one prefill worker, one
decode worker, and the front-door router — exactly as a deployment would
(``python -m infinistore_tpu.serve --role prefill|decode|router``; the
in-process ``local_fleet`` used by tests and benches shares one
interpreter and is NOT this), then drives a few completions through the
router and verifies the handoff chain end to end:

    client -> router -> prefill worker --(store push + flush)-->
           -> decode worker --(store adoption)--> SSE tokens back

Usage::

    python examples/fleet_processes.py            # demo: prints progress
    python examples/fleet_processes.py --smoke    # CI: exit 0 iff every
                                                  # request completed and
                                                  # the router served no 5xx

Everything runs on localhost with the tiny random-init model and TCP
store connections, so it works on any host (no TPU, no checkpoints).
The SAME topology spread across real machines — which flags change,
which don't, and the cross-host gotchas — is documented in
``docs/fleet_multihost.md``.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_http(port: int, path: str, deadline: float, proc=None) -> None:
    while True:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(
                f"process died while waiting for :{port}{path} "
                f"(rc={proc.returncode})"
            )
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=1.0
            ):
                return
        except Exception:
            if time.time() >= deadline:
                raise RuntimeError(f"port {port}{path} did not come up")
            time.sleep(0.2)


def wait_tcp(port: int, deadline: float, proc=None) -> None:
    while True:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(f"process died (rc={proc.returncode})")
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.5).close()
            return
        except OSError:
            if time.time() >= deadline:
                raise RuntimeError(f"port {port} did not come up")
            time.sleep(0.1)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI mode: exit nonzero unless every request "
                         "completes and the router serves zero 5xx")
    ap.add_argument("--requests", type=int, default=8)
    args = ap.parse_args()

    env = {
        **os.environ,
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        # a cold fleet's jit-compile storm must not trip the burn
        # watchdogs / predictive shed during bring-up
        "ISTPU_SLO_TTFT_S": os.environ.get("ISTPU_SLO_TTFT_S", "60"),
        "ISTPU_SLO_TPOT_S": os.environ.get("ISTPU_SLO_TPOT_S", "10"),
    }
    store_port, store_mport = free_port(), free_port()
    pf_port, dec_port, router_port = free_port(), free_port(), free_port()
    procs = []

    def spawn(label, argv):
        print(f"[fleet] starting {label}: {' '.join(argv[2:])}",
              flush=True)
        p = subprocess.Popen(argv, cwd=REPO, env=env)
        procs.append(p)
        return p

    try:
        store = spawn("store", [
            sys.executable, "-m", "infinistore_tpu.server",
            "--service-port", str(store_port),
            "--manage-port", str(store_mport),
            "--prealloc-size", "1", "--minimal-allocate-size", "16",
            "--log-level", "warning", "--backend", "python",
        ])
        wait_tcp(store_port, time.time() + 30, store)

        worker_flags = [
            "--model", "tiny", "--block-tokens", "4", "--n-blocks", "128",
            "--store-host", "127.0.0.1",
            "--store-service-port", str(store_port),
            "--store-connection", "tcp", "--log-level", "warning",
        ]
        prefill = spawn("prefill worker", [
            sys.executable, "-m", "infinistore_tpu.serve",
            "--role", "prefill", "--port", str(pf_port), *worker_flags,
        ])
        decode = spawn("decode worker", [
            sys.executable, "-m", "infinistore_tpu.serve",
            "--role", "decode", "--port", str(dec_port), *worker_flags,
        ])
        # workers import jax + build engines before listening: generous
        # deadline, both booting in parallel
        wait_http(pf_port, "/healthz", time.time() + 180, prefill)
        wait_http(dec_port, "/healthz", time.time() + 180, decode)

        router = spawn("router", [
            sys.executable, "-m", "infinistore_tpu.serve",
            "--role", "router", "--port", str(router_port),
            "--prefill-workers", f"127.0.0.1:{pf_port}",
            "--decode-workers", f"127.0.0.1:{dec_port}",
            "--log-level", "warning",
        ])
        wait_http(router_port, "/healthz", time.time() + 30, router)

        url = f"http://127.0.0.1:{router_port}"
        completed = failed = 0
        for i in range(args.requests):
            body = json.dumps({
                "prompt": [(i * 7 + j) % 200 + 1 for j in range(16)],
                "max_tokens": 4, "temperature": 0,
            }).encode()
            req = urllib.request.Request(
                url + "/v1/completions", data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=300) as r:
                    out = json.load(r)
                toks = out["choices"][0]["token_ids"]
                assert r.status == 200 and len(toks) == 4, out
                completed += 1
                print(f"[fleet] request {i}: 200, tokens={toks}",
                      flush=True)
            except Exception as e:  # noqa: BLE001 — counted, reported
                failed += 1
                print(f"[fleet] request {i} FAILED: {e!r}", flush=True)

        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            prom = r.read().decode()
        m = re.search(r'istpu_fd_requests_total\{class="5xx"\} (\S+)', prom)
        fivexx = float(m.group(1)) if m else 0.0
        with urllib.request.urlopen(url + "/debug/fleet", timeout=10) as r:
            fleet = json.load(r)
        print(f"[fleet] done: {completed}/{args.requests} completed, "
              f"{failed} failed, router 5xx={fivexx:.0f}, workers="
              f"{[w.get('role') for w in fleet.get('workers', [])]}",
              flush=True)
        ok = completed == args.requests and failed == 0 and fivexx == 0.0
        if args.smoke and not ok:
            print("[fleet] SMOKE FAILED", flush=True)
            return 1
        print("[fleet] OK", flush=True)
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    sys.exit(main())
