"""PD-disaggregation, prefill node.

The deployment the store exists for (reference docs/source/design.rst:46-63:
a prefill pool computes KV once, a decode pool consumes it): THIS process
owns prompt ingestion.  It prefills the prompt on its own engine and the
paged KV streams to the store chunk-by-chunk, flushed before exit — nothing
else is handed to the decode node; discovery happens through the store's
prefix index (``get_match_last_index``).

Run a store server first, then:

    python examples/disagg_prefill.py --service-port 22345 \
        --prompt 11,42,7,99,5,3,17,28,64,1,2

The decode node (``disagg_decode.py``) may run on another host pointed at
the same store (TCP transport) — the pair is the two-pool topology the
reference's demo drives with vLLM.

Prints one JSON line: {"model_id", "n_tokens", "chunks_stored"}.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import infinistore_tpu as ist
from infinistore_tpu.engine import InferenceEngine
from infinistore_tpu.kv import PagedCacheConfig
from infinistore_tpu.models import TINY, init_params, scaled


def build_engine(args, conn):
    """Both nodes must run the SAME model; the demo uses the deterministic
    random-init TINY config (seed 0) as a stand-in for loading one shared
    checkpoint on each node (models/hf.py params_from_hf)."""
    import jax.numpy as jnp

    cfg = scaled(TINY, dtype=jnp.dtype(args.dtype).type)
    params = init_params(cfg, jax.random.PRNGKey(0))
    pc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, block_tokens=args.block_tokens, n_blocks=256,
        dtype=cfg.dtype,
    )
    return InferenceEngine(params, cfg, pc, conn=conn,
                           model_id=args.model_id,
                           kv_quant=(None if args.kv_quant == "none"
                                     else args.kv_quant))


def add_common_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--service-port", type=int, required=True)
    ap.add_argument("--connection", choices=["tcp", "shm"], default="tcp",
                    help="tcp = the cross-host (DCN) transport; shm = "
                         "zero-copy, same host only")
    ap.add_argument("--prompt", required=True,
                    help="comma-separated token ids")
    ap.add_argument("--model-id", default="disagg-demo",
                    help="store key namespace; must match on both nodes")
    ap.add_argument("--block-tokens", type=int, default=4)
    ap.add_argument("--dtype", default="float32",
                    help="float32 keeps the two nodes bit-identical")
    ap.add_argument("--kv-quant", choices=["int8", "none"], default="none",
                    help="store-hop page format.  This demo defaults to "
                         "'none' (lossless) because its verification "
                         "recipe is decode-node tokens == monolithic "
                         "decode, which int8 noise can break; the library "
                         "default is int8 (half the transfer bytes)")


def connect(args) -> "ist.InfinityConnection":
    conn = ist.InfinityConnection(ist.ClientConfig(
        host_addr=args.host, service_port=args.service_port,
        connection_type=(ist.TYPE_TCP if args.connection == "tcp"
                         else ist.TYPE_SHM),
    ))
    conn.connect()
    return conn


def main() -> None:
    ap = argparse.ArgumentParser("disagg_prefill")
    add_common_args(ap)
    args = ap.parse_args()
    prompt = [int(t) for t in args.prompt.split(",")]

    conn = connect(args)
    eng = build_engine(args, conn)
    st = eng.prefill(prompt)  # KV streams to the store chunk by chunk
    # durability barrier before signaling hand-off: a no-op under the
    # default strict mode, the REQUIRED join under store_durability=
    # "relaxed" (decode nodes may only be pointed at flushed prefixes)
    eng.store_flush()
    print(json.dumps({
        "model_id": args.model_id,
        "n_tokens": len(st.tokens),
        "chunks_stored": len(prompt) // args.block_tokens,
    }))
    eng.release(st)
    conn.close()


if __name__ == "__main__":
    main()
