"""Synchronous zero-copy client example.

Batched multi-block put/get through the SHM transport (the RDMA analog;
reference parity: infinistore/example/client.py).  Start a server first:

    python -m infinistore_tpu.server --service-port 22345 --manage-port 18080
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import uuid

import numpy as np

import infinistore_tpu as ist


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1")
    ap.add_argument("--service-port", type=int, default=22345)
    ap.add_argument("--block-size", type=int, default=32, help="KiB per block")
    ap.add_argument("--blocks", type=int, default=16)
    args = ap.parse_args()

    conn = ist.InfinityConnection(
        ist.ClientConfig(
            host_addr=args.server,
            service_port=args.service_port,
            connection_type=ist.TYPE_SHM,
        )
    )
    conn.connect()

    bs = args.block_size << 10
    src = np.random.randint(0, 256, size=args.blocks * bs, dtype=np.uint8)
    conn.register_mr(src)

    run = uuid.uuid4().hex[:8]
    blocks = [(f"example-{run}-{i}", i * bs) for i in range(args.blocks)]
    conn.write_cache(blocks, bs, src.ctypes.data)
    print(f"wrote {args.blocks} x {args.block_size} KiB")

    dst = np.zeros_like(src)
    conn.register_mr(dst)
    conn.read_cache(blocks, bs, dst.ctypes.data)
    assert np.array_equal(src, dst), "round-trip mismatch"
    print("read back OK; prefix match:",
          conn.get_match_last_index([k for k, _ in blocks]))
    conn.delete_keys([k for k, _ in blocks])
    conn.close()


if __name__ == "__main__":
    main()
