"""One rank of a REAL two-process multi-host deployment.

The reference's cluster story is N server nodes + N client ranks over an
RDMA/TCP fabric (reference docs/source/design.rst:46-63); this worker is
the TPU-native rank shape: the JAX distributed runtime ties the
processes into ONE global device mesh for collectives, while the store
ties them together at the KV layer over TCP (the DCN analog).  Each rank

1. ``jax.distributed.initialize``s against the coordinator (the thing
   the in-process dryrun could never prove — VERDICT r4 missing #3),
2. runs the full sharded TRAIN step over a hybrid dp(DCN) x tp(ICI)
   mesh spanning BOTH processes — the dp psum crosses the process
   boundary through real collectives (gloo on CPU hosts, ICI/DCN on
   TPU pods),
3. serves with a process-LOCAL tp mesh (dp-over-DCN serving: request
   rows are embarrassingly parallel across hosts, so serving needs no
   cross-process collectives — hosts share KV through the store
   instead): rank 0 prefills and durably flushes; rank 1 then prefills
   the same prompt and must hit the store-resident prefix over TCP,
4. writes its results as one JSON line for the harness to compare.

Launch (the test does this; 4 virtual CPU devices per process):

    XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
    python examples/multihost_worker.py --process-id 0 --num-processes 2 \
        --coordinator-port 9999 --store-port 26001 --out r0.json &
    ... --process-id 1 ... --out r1.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser("multihost_worker")
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--coordinator-port", type=int, required=True)
    ap.add_argument("--store-port", type=int, required=True)
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=4")
    import jax

    from infinistore_tpu.parallel.distributed import (
        initialize,
        make_hybrid_mesh,
    )

    initialize(
        coordinator_address=f"127.0.0.1:{args.coordinator_port}",
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.process_count() == args.num_processes

    import numpy as np

    import infinistore_tpu as ist
    from infinistore_tpu.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models import TINY, init_params, scaled
    from infinistore_tpu.parallel.train import make_train_step

    # -- leg 1: global hybrid mesh, cross-process train step ----------
    mesh = make_hybrid_mesh(tp=2)  # dp spans DCN (the 2 processes)
    assert mesh.shape["tp"] == 2 and mesh.shape["dp"] >= 2
    cfg = scaled(TINY, dtype=np.float32)
    key = jax.random.PRNGKey(0)
    params = init_params(cfg, key)  # deterministic -> identical per rank
    from jax.sharding import NamedSharding, PartitionSpec as P

    from infinistore_tpu.parallel.train import llama_param_specs

    specs = llama_param_specs(cfg)
    params = jax.tree.map(
        lambda p, s: jax.make_array_from_callback(
            p.shape, NamedSharding(mesh, s), lambda idx, _p=p: _p[idx]
        ),
        params,
        specs,
    )
    step = make_train_step(cfg, mesh, lr=1e-2)
    B, S = 8, 16
    rng = np.random.RandomState(0)
    toks_np = rng.randint(1, cfg.vocab_size, size=(B, S)).astype(np.int32)
    tokens = jax.make_array_from_callback(
        (B, S), NamedSharding(mesh, P("dp", "sp")),
        lambda idx: toks_np[idx],
    )
    losses = []
    for _ in range(2):
        params, loss = step(params, tokens)
        losses.append(float(np.asarray(loss)))

    # -- leg 2: dp-over-DCN serving with store-mediated prefix reuse --
    from jax.sharding import Mesh

    local = Mesh(np.asarray(jax.local_devices()[:2]), ("tp",))
    scfg = scaled(TINY, dtype=np.float32)
    sparams = init_params(scfg, jax.random.PRNGKey(7))
    pc = PagedCacheConfig(
        n_layers=scfg.n_layers, n_kv_heads=scfg.n_kv_heads,
        head_dim=scfg.head_dim, n_blocks=64, block_tokens=4,
        dtype=scfg.dtype,
    )
    conn = ist.InfinityConnection(ist.ClientConfig(
        host_addr="127.0.0.1", service_port=args.store_port,
        connection_type=ist.TYPE_TCP,  # the cross-host (DCN) transport
    ))
    conn.connect()
    eng = InferenceEngine(
        sparams, scfg, pc, conn=conn, model_id="mh-demo", mesh=local,
        kv_quant=None,  # lossless: ranks must agree token-for-token
    )
    # a tail past the page boundary: both complete chunks are then
    # store-reusable (a page-aligned prompt recomputes its final chunk
    # for the last-position logits)
    prompt = [11, 42, 7, 99, 5, 3, 17, 28, 64, 1]
    from jax.experimental import multihost_utils

    if args.process_id == 0:
        st = eng.prefill(prompt)
        toks = eng.decode(st, 12)
        reused = st.reused_chunks
        eng.store_flush()  # durability barrier before rank 1 looks
        multihost_utils.sync_global_devices("mh-kv-ready")
    else:
        multihost_utils.sync_global_devices("mh-kv-ready")
        st = eng.prefill(prompt)  # must hit rank 0's pages over TCP
        toks = eng.decode(st, 12)
        reused = st.reused_chunks
    eng.release(st)
    conn.close()

    with open(args.out, "w") as f:
        json.dump({
            "pid": args.process_id,
            "n_global_devices": len(jax.devices()),
            "mesh_shape": dict(mesh.shape),
            "losses": losses,
            "tokens": toks,
            "reused_chunks": reused,
        }, f)
    # ranks exit together (a dangling coordinator would hang the peer)
    multihost_utils.sync_global_devices("mh-done")


if __name__ == "__main__":
    main()
