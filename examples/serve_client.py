"""Drive the HTTP serving front-end (infinistore_tpu.serve).

Start a server first:
    python -m infinistore_tpu.serve --model tiny --port 8000

Then:
    python examples/serve_client.py --port 8000
"""

import argparse
import http.client
import json


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    args = ap.parse_args()

    conn = http.client.HTTPConnection(args.host, args.port, timeout=300)

    # model card
    conn.request("GET", "/v1/models")
    print("models:", json.loads(conn.getresponse().read()))

    # one-shot completion (token ids in, token ids out; temperature 0 =
    # greedy — pair with your tokenizer of choice outside the engine)
    prompt = [11, 42, 7, 99, 5, 3, 17, 28]
    conn.request("POST", "/v1/completions", json.dumps({
        "prompt": prompt, "max_tokens": 16, "temperature": 0,
    }), {"Content-Type": "application/json"})
    print("completion:", json.loads(conn.getresponse().read()))

    # streaming (SSE): tokens arrive at decode-chunk granularity
    conn.request("POST", "/v1/completions", json.dumps({
        "prompt": prompt, "max_tokens": 16, "temperature": 0.8,
        "top_p": 0.95, "stream": True,
    }), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            event, buf = buf.split(b"\n\n", 1)
            payload = event[len(b"data: "):]
            if payload == b"[DONE]":
                print("stream: [DONE]")
                conn.close()
                return
            print("stream:", json.loads(payload)["choices"][0]["token_ids"])


if __name__ == "__main__":
    main()
