"""Drive the HTTP serving front-end (infinistore_tpu.serve).

Start a server first:
    python -m infinistore_tpu.serve --model tiny --port 8000
or, for text in / text out, point it at an HF checkpoint dir (its tokenizer
is loaded automatically; --tokenizer overrides):
    python -m infinistore_tpu.serve --model /path/to/llama --port 8000

Then:
    python examples/serve_client.py --port 8000                    # token ids
    python examples/serve_client.py --port 8000 --text "Hello"     # text
"""

import argparse
import http.client
import json


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--text", default=None,
                    help="send a STRING prompt (server must have a "
                         "tokenizer); responses then carry text")
    ap.add_argument("--stop", default=None,
                    help="stop string (text mode): output is truncated "
                         "before its first occurrence")
    args = ap.parse_args()

    conn = http.client.HTTPConnection(args.host, args.port, timeout=300)

    # model card
    conn.request("GET", "/v1/models")
    print("models:", json.loads(conn.getresponse().read()))

    # prompt: a string when the server has a tokenizer, else token ids
    prompt = args.text if args.text is not None else [11, 42, 7, 99, 5, 3, 17, 28]

    # one-shot completion (temperature 0 = greedy)
    body = {"prompt": prompt, "max_tokens": 16, "temperature": 0}
    if args.stop:
        body["stop"] = [args.stop]
    conn.request("POST", "/v1/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    out = json.loads(conn.getresponse().read())
    choice = out["choices"][0]
    print("completion:", choice.get("text", choice["token_ids"]))

    # streaming (SSE): deltas arrive at decode-chunk granularity — text
    # deltas when the server detokenizes, token ids otherwise
    conn.request("POST", "/v1/completions", json.dumps({
        "prompt": prompt, "max_tokens": 16, "temperature": 0.8,
        "top_p": 0.95, "stream": True,
    }), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            event, buf = buf.split(b"\n\n", 1)
            payload = event[len(b"data: "):]
            if payload == b"[DONE]":
                print("stream: [DONE]")
                conn.close()
                return
            c = json.loads(payload)["choices"][0]
            print("stream:", c.get("text", c["token_ids"]))


if __name__ == "__main__":
    main()
