"""Async client example: many in-flight batched transfers.

Mirrors the reference's asyncio example (infinistore/example/client_async.py):
one connection, a semaphore-bounded flood of write_cache_async /
read_cache_async calls -- the layer-by-layer prefill streaming pattern.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import asyncio
import time
import uuid

import numpy as np

import infinistore_tpu as ist


async def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1")
    ap.add_argument("--service-port", type=int, default=22345)
    ap.add_argument("--layers", type=int, default=32)
    ap.add_argument("--blocks", type=int, default=16)
    ap.add_argument("--block-size", type=int, default=64, help="KiB")
    args = ap.parse_args()

    conn = ist.InfinityConnection(
        ist.ClientConfig(
            host_addr=args.server,
            service_port=args.service_port,
            connection_type=ist.TYPE_SHM,
        )
    )
    await conn.connect_async()

    bs = args.block_size << 10
    buf = np.random.randint(0, 256, size=args.blocks * bs, dtype=np.uint8)
    conn.register_mr(buf)
    run = uuid.uuid4().hex[:8]

    # one write per "layer", all in flight (bounded by the conn semaphore)
    t0 = time.perf_counter()
    await asyncio.gather(*[
        conn.write_cache_async(
            [(f"{run}-L{layer}-b{i}", i * bs) for i in range(args.blocks)],
            bs, buf.ctypes.data,
        )
        for layer in range(args.layers)
    ])
    dt = time.perf_counter() - t0
    total = args.layers * args.blocks * bs
    print(f"async wrote {total / 1e6:.0f} MB in {dt:.3f}s = {total / dt / 1e9:.2f} GB/s")

    dst = np.zeros_like(buf)
    conn.register_mr(dst)
    t0 = time.perf_counter()
    await asyncio.gather(*[
        conn.read_cache_async(
            [(f"{run}-L{layer}-b{i}", i * bs) for i in range(args.blocks)],
            bs, dst.ctypes.data,
        )
        for layer in range(args.layers)
    ])
    dt = time.perf_counter() - t0
    print(f"async read back in {dt:.3f}s = {total / dt / 1e9:.2f} GB/s")
    assert np.array_equal(buf, dst)
    conn.close()


if __name__ == "__main__":
    asyncio.run(main())
