"""PD-disaggregation, decode node.

Counterpart of ``disagg_prefill.py`` (reference docs/source/design.rst:46-63
two-pool topology): THIS process never computes the prompt's KV.  Its
engine's prefill discovers the stored prefix through the store's index
(``get_match_last_index`` under ``KVTransferEngine.lookup_prefix``), pulls
those pages over the transport into its own HBM paged cache, computes only
the sub-chunk tail, and decodes.

    python examples/disagg_decode.py --service-port 22345 \
        --prompt 11,42,7,99,5,3,17,28,64,1,2 --steps 8

Prints one JSON line: {"reused_chunks", "tokens"} — ``reused_chunks`` > 0
is the proof the prompt's KV came from the prefill node, not recompute;
``tokens`` must equal the same model's monolithic decode.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from disagg_prefill import add_common_args, build_engine, connect  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser("disagg_decode")
    add_common_args(ap)
    ap.add_argument("--steps", type=int, default=8)
    args = ap.parse_args()
    prompt = [int(t) for t in args.prompt.split(",")]

    conn = connect(args)
    eng = build_engine(args, conn)
    st = eng.prefill(prompt)  # pulls the prefill node's pages from the store
    toks = eng.decode(st, args.steps)
    print(json.dumps({
        "reused_chunks": st.reused_chunks,
        "tokens": toks,
    }))
    eng.release(st)
    conn.close()


if __name__ == "__main__":
    main()
