"""Long-context serving: sequence-parallel prompt ingestion, paged decode.

The reference's cluster serves long prompts by scaling the prefill tier
(design.rst's prefill/decode disaggregation); the TPU-native analog for
ONE long prompt is sequence parallelism — shard the prompt over an
``sp`` axis, run ring attention (per-device attention memory
O((S/sp)^2), FLOPs spread over the group), then hand the KV to a paged
engine for decode:

1. ``parallel.sharding.make_sp_prefill``: the sp x tp prefill (ring
   attention inside a shard_map), returning logits + KV in the engine's
   exact cache contract;
2. ``InferenceEngine.adopt_prefill``: the public ingestion point — pages
   the external KV into the HBM cache and returns a decode-ready state;
3. plain paged decode.

Runs anywhere (CPU mesh by default):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/long_context.py --seq 512 --sp 2 --tp 2
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser("long_context")
    ap.add_argument("--seq", type=int, default=512,
                    help="prompt length (padded to sp x pages)")
    ap.add_argument("--sp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--new-tokens", type=int, default=16)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from infinistore_tpu.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models import TINY, init_params, scaled
    from infinistore_tpu.parallel import MeshShape, make_mesh
    from infinistore_tpu.parallel.sharding import (
        llama_inference_specs,
        make_sp_prefill,
        shard_params,
    )

    cfg = scaled(TINY, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    T = 16
    S = args.seq - args.seq % (args.sp * T)  # whole pages on every shard
    prompt = [int(x) for x in
              np.random.RandomState(1).randint(1, cfg.vocab_size, size=S)]

    n = args.sp * args.tp
    mesh = make_mesh(MeshShape(sp=args.sp, tp=args.tp),
                     devices=jax.devices()[:n])
    with jax.set_mesh(mesh):
        sharded = shard_params(params, mesh,
                               specs=llama_inference_specs(cfg=cfg))
        fn = make_sp_prefill(cfg, mesh)
        t0 = time.perf_counter()
        logits, kv = fn(sharded, jnp.asarray([prompt], jnp.int32))
        jax.block_until_ready(kv)
        dt = time.perf_counter() - t0
    print(f"sp={args.sp} x tp={args.tp} prefill of {S} tokens: "
          f"{dt * 1e3:.1f} ms "
          f"(per-device attention window {S // args.sp} positions)")

    eng = InferenceEngine(params, cfg, PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, block_tokens=T,
        n_blocks=S // T + args.new_tokens // T + 8, dtype=cfg.dtype,
    ))
    st = eng.adopt_prefill(prompt, jnp.asarray(kv),
                           jnp.asarray(logits)[0, -1])
    toks = eng.decode(st, args.new_tokens)
    print(f"decoded {len(toks)} tokens from the adopted KV: {toks[:8]}...")

    # sanity: identical to prefilling inside the engine
    ref = InferenceEngine(params, cfg, PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, block_tokens=T,
        n_blocks=S // T + args.new_tokens // T + 8, dtype=cfg.dtype,
    ))
    want = ref.decode(ref.prefill(prompt), args.new_tokens)
    assert toks == want, "sp-ingested decode diverged from engine prefill"
    print("matches the engine's own prefill+decode exactly")


if __name__ == "__main__":
    main()
