"""Serving SLO benchmark: open-loop goodput-vs-rate curve.

Sweeps arrival rates against a live serving front-end (or a self-hosted
tiny-model server with ``--self-serve``) through the open-loop harness
(`infinistore_tpu/loadgen.py`): Poisson/deterministic arrivals,
concurrent streaming sessions, a shared-prefix request population, and
per-lane TTFT/TPOT percentiles.  The headline output is **goodput** —
requests/s that complete AND meet the TTFT+TPOT SLOs — per offered
rate, the curve ROADMAP item 4's admission/QoS work will be judged
against.

    # against a running server
    python bench_serve.py --url http://127.0.0.1:8000 --rates 2,4,8 \
        --n 64 --slo-ttft 2.0 --slo-tpot 0.25 --json-out serve_load.json

    # zero-setup smoke (in-process tiny model; CI uses this)
    JAX_PLATFORMS=cpu python bench_serve.py --self-serve --rates 8,16 --n 24

``--json-out`` writes one JSON object joining the bench-schema family
(``run_id`` + stable keys; docs/observability.md): ``{run_id, kind:
"serve_load", slo: {...}, config: {...}, curve: [per-rate summaries],
stepprof: {...}, health: {...}}`` — ``stepprof`` is the server's
step-profiler summary (``GET /debug/engine``): host-stall share,
retrace pressure, dispatch counts for the whole sweep; ``health`` is
the health plane's verdict (``GET /debug/health``): alert firing
transitions and the peak burn rate observed, with ``alerts_fired``
mirrored top-level for the trend table (both absent against servers
without the endpoints); ``admission`` is the overload-control verdict —
client-observed 429 shed counts per lane, the server's
``GET /debug/admission`` shed/quota tallies, and the ``plateau`` flag
(goodput at the highest offered rate held ≥50% of the curve's peak
instead of collapsing), with ``goodput_plateau`` mirrored top-level.

``--conversation`` switches the sweep to multi-turn session traffic
(``SessionConfig`` in loadgen.py): rates become session arrivals/s and
the record gains a ``sessions`` block — ``reprefill_waste_frac`` and
``affinity_hit_rate`` (both mirrored top-level for the trend table)
plus the client-observed per-turn TTFT slope, the three numbers of the
cross-turn KV-persistence contract.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import uuid

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def parse_rates(s: str):
    rates = [float(x) for x in s.split(",") if x.strip()]
    if not rates:
        raise argparse.ArgumentTypeError("need at least one rate")
    return rates


def parse_mix(s: str):
    """``weight:prompt:max_tokens`` triples, comma-separated — e.g.
    ``3:24:8,1:96:32`` = 3/4 short chat turns, 1/4 long generations."""
    mix = []
    for part in s.split(","):
        w, p, m = part.split(":")
        mix.append((float(w), int(p), int(m)))
    return mix


def parse_weighted_ints(s: str):
    """``weight:value`` pairs, comma-separated — the turn-count and
    turn-token mixes of ``--conversation`` (e.g. ``3:4,1:8`` = 3/4 of
    sessions run 4 turns, 1/4 run 8)."""
    out = []
    for part in s.split(","):
        w, v = part.split(":")
        out.append((float(w), int(v)))
    return out


def parse_think(s: str):
    """``lo:hi`` uniform think-time range in seconds (``0:0`` =
    agent-loop speed)."""
    lo, hi = s.split(":")
    return (float(lo), float(hi))


def parse_lanes(s: str):
    """``lane:weight`` pairs, comma-separated — e.g. ``10:1,0:4`` = 1
    in 5 requests rides the high-priority lane.  A lane may be an int
    priority or a STRING tenant id (``acme:3,bulk:1``): named tenants
    carry through as the lane label everywhere (metrics, quotas, the
    usage ledger)."""
    lanes = []
    for part in s.split(","):
        lane, w = part.rsplit(":", 1)
        lane = lane.strip()
        lanes.append((int(lane) if lane.lstrip("-").isdigit() else lane,
                      float(w)))
    return lanes


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_store():
    """A python-backend store node in a subprocess (the disagg fleet's
    KV transport).  Returns ``(proc, service_port)``; caller SIGINTs."""
    import socket
    import subprocess

    port, mport = _free_port(), _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "infinistore_tpu.server",
         "--service-port", str(port), "--manage-port", str(mport),
         "--prealloc-size", "1", "--minimal-allocate-size", "16",
         "--log-level", "warning", "--backend", "python"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    deadline = time.time() + 25
    while True:
        if proc.poll() is not None:
            raise RuntimeError("store server failed to start")
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.5).close()
            return proc, port
        except OSError:
            if time.time() >= deadline:
                proc.kill()
                raise RuntimeError("store server did not come up")
            time.sleep(0.1)


def self_disagg(args):
    """The zero-setup disaggregated fleet: one store node (subprocess)
    + N in-process prefill workers + M decode workers behind a
    ``FrontDoor`` — the target the ``disagg`` block is measured
    against.  Returns ``(close, url, vocab, fleet_workers)``."""
    import signal

    import jax.numpy as jnp

    from infinistore_tpu.frontdoor import local_fleet
    from infinistore_tpu.models import TINY, scaled

    proc, store_port = _spawn_store()
    try:
        fd, workers, close_fleet = local_fleet(
            store_port, args.prefill_workers, args.decode_workers,
            n_blocks=args.self_serve_blocks,
            max_batch=args.self_serve_batch,
            n_routers=max(1, args.routers),
        )
    except BaseException:
        proc.send_signal(signal.SIGINT)
        proc.wait(timeout=10)
        raise

    def close():
        close_fleet()
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except Exception:  # noqa: BLE001
            proc.kill()

    cfg = scaled(TINY, dtype=jnp.float32)
    return close, f"http://127.0.0.1:{fd.port}", cfg.vocab_size, workers


def self_serve(args):
    """An in-process tiny-model ServingServer on a free port: the
    zero-setup target for smokes — real HTTP, real scheduler, no
    checkpoint or separate process needed."""
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from infinistore_tpu.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models import TINY, init_params, scaled
    from infinistore_tpu.serve import ServingServer

    cfg = scaled(TINY, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    pc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, n_blocks=args.self_serve_blocks,
        block_tokens=4, dtype=cfg.dtype,
    )
    eng = InferenceEngine(params, cfg, pc)
    eng.decode_chunk = 4
    srv = ServingServer(eng, port=0, max_batch=args.self_serve_batch,
                        model_id="tiny-bench",
                        slo_ttft_s=args.slo_ttft, slo_tpot_s=args.slo_tpot,
                        quotas=args.quotas or None)
    srv.start()
    return srv, f"http://127.0.0.1:{srv.port}", cfg.vocab_size


def _lane_pct(point, which, key):
    """Completed-weighted mean of one lane percentile across a point's
    lanes — the cross-lane headline the disagg ratios compare on."""
    tot = n = 0.0
    for v in point["lanes"].values():
        stats = v.get(which) or {}
        if stats.get(key) is not None and v.get("completed"):
            tot += stats[key] * v["completed"]
            n += v["completed"]
    return (tot / n) if n else None


def _gather_disagg(url, workers, args):
    """The ``disagg`` block's fleet-side half: the front door's
    /debug/fleet (handoff percentiles, per-role counts) plus the decode
    workers' ledgers (per-request adoption provenance — the store/local
    split is process-global in-process, the ledger is per-worker)."""
    import urllib.request

    fleet = None
    try:
        with urllib.request.urlopen(url + "/debug/fleet", timeout=5) as r:
            fleet = json.loads(r.read())
    except Exception:  # noqa: BLE001 — observability, not the bench
        pass
    adopted = total = 0
    for s in (workers or {}).get("decode", ()):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{s.port}/debug/requests",
                    timeout=5) as r:
                recs = json.loads(r.read()).get("records") or []
        except Exception:  # noqa: BLE001
            continue
        for rec in recs:
            st = rec.get("store") or {}
            total += 1
            if (st.get("reused_chunks") or 0) > 0:
                adopted += 1
    out = {
        "prefill_workers": args.prefill_workers,
        "decode_workers": args.decode_workers,
        "adoption": {
            "requests": total, "adopted": adopted,
            "hit_rate": round(adopted / total, 4) if total else None,
        },
    }
    if fleet and fleet.get("enabled"):
        out["handoff_ms"] = fleet.get("handoff")
        out["fleet_adoption_tokens"] = fleet.get("adoption")
        out["router_requests"] = fleet.get("requests")
    return out


def main(argv=None) -> int:
    from infinistore_tpu.loadgen import LoadConfig, sweep

    ap = argparse.ArgumentParser("bench_serve.py")
    ap.add_argument("--url", default=None,
                    help="serving front-end base URL (http://host:8000)")
    ap.add_argument("--target", dest="url",
                    help="alias of --url: point it at a disaggregated "
                         "front door (istpu-frontdoor) to drive a fleet")
    ap.add_argument("--self-serve", action="store_true",
                    help="spin up an in-process tiny-model server to "
                         "load instead of --url (CI smoke mode)")
    ap.add_argument("--self-disagg", action="store_true",
                    help="spin up a whole in-process disaggregated "
                         "fleet (store node + prefill + decode workers "
                         "+ front door), sweep it, then sweep a "
                         "same-decode-budget monolith and report the "
                         "TTFT/TPOT ratios in a `disagg` block")
    ap.add_argument("--prefill-workers", type=int, default=1,
                    help="--self-disagg: prefill pool size")
    ap.add_argument("--decode-workers", type=int, default=1,
                    help="--self-disagg: decode pool size")
    ap.add_argument("--routers", type=int, default=1,
                    help="--self-disagg: router replicas over the same "
                         "pools (each names the others as --peers); the "
                         "load generator spreads clients across all of "
                         "them and fails over on connect errors")
    ap.add_argument("--pacer", choices=["auto", "thread", "async"],
                    default="auto",
                    help="arrival pacer: 'async' drives every request "
                         "from one asyncio event loop (the 10k-session "
                         "path), 'thread' keeps one thread per in-flight "
                         "request; 'auto' picks async for live targets")
    ap.add_argument("--no-monolith-baseline", action="store_true",
                    help="--self-disagg: skip the monolith comparison "
                         "sweep (faster; no ratio in the output)")
    ap.add_argument("--self-serve-blocks", type=int, default=512)
    ap.add_argument("--self-serve-batch", type=int, default=8)
    ap.add_argument("--rates", type=parse_rates, default=[2.0, 4.0, 8.0],
                    help="comma-separated arrival rates (req/s) to sweep")
    ap.add_argument("--n", type=int, default=32,
                    help="requests per rate point")
    ap.add_argument("--process", choices=["poisson", "deterministic"],
                    default="poisson")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mix", type=parse_mix, default=[(1.0, 24, 8)],
                    help="weight:prompt_tokens:max_tokens triples, "
                         "comma-separated (default 1:24:8)")
    ap.add_argument("--lanes", type=parse_lanes, default=[(0, 1.0)],
                    help="lane:weight pairs, comma-separated (default "
                         "0:1 — one lane).  Lanes are int priorities OR "
                         "string tenant ids: '--lanes acme:3,bulk:1' "
                         "names tenants end to end (metrics, --quota, "
                         "the usage ledger)")
    ap.add_argument("--prefixes", type=int, default=4,
                    help="shared-prefix population size (0 disables)")
    ap.add_argument("--prefix-len", type=int, default=16)
    ap.add_argument("--prefix-frac", type=float, default=0.5,
                    help="fraction of requests that prepend a shared "
                         "prefix (tenant system-prompt traffic shape)")
    ap.add_argument("--vocab", type=int, default=256,
                    help="token ids drawn in [0, vocab) — keep within "
                         "the served model's vocab")
    ap.add_argument("--no-stream", action="store_true",
                    help="non-streaming requests (TTFT == e2e)")
    ap.add_argument("--honor-retry-after", action="store_true",
                    help="a 429-shed request sleeps the server's "
                         "Retry-After (capped 10 s) and re-attempts "
                         "once; default off — the raw shed behavior is "
                         "the measurement")
    ap.add_argument("--quota", action="append", default=[],
                    dest="quotas", metavar="TENANT:TOKS_PER_S[:BURST_S]",
                    help="--self-serve only: per-tenant token quotas "
                         "passed through to the in-process server")
    ap.add_argument("--conversation", action="store_true",
                    help="conversation mode: --rates become SESSION "
                         "arrivals/s, each session runs its turns "
                         "sequentially with per-turn context growth and "
                         "a 'session' id end to end; the record gains a "
                         "`sessions` block (reprefill_waste_frac, "
                         "affinity_hit_rate, per-turn TTFT slope)")
    ap.add_argument("--sessions", type=int, default=16,
                    help="--conversation: sessions per rate point")
    ap.add_argument("--turns", type=parse_weighted_ints,
                    default=[(1.0, 4)],
                    help="--conversation: weight:n_turns mix "
                         "(default 1:4)")
    ap.add_argument("--turn-tokens", type=parse_weighted_ints,
                    default=[(1.0, 16)],
                    help="--conversation: weight:new_user_tokens mix "
                         "per turn (default 1:16)")
    ap.add_argument("--system-prompt-len", type=int, default=32,
                    help="--conversation: shared system-prompt tokens "
                         "every session opens on")
    ap.add_argument("--think", type=parse_think, default=(0.0, 0.0),
                    help="--conversation: lo:hi uniform think-time "
                         "seconds between turns (default 0:0)")
    ap.add_argument("--conv-max-tokens", type=int, default=8,
                    help="--conversation: max_tokens per turn")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--slo-ttft", type=float,
                    default=float(os.environ.get("ISTPU_SLO_TTFT_S", 2.0)),
                    help="TTFT SLO in seconds (goodput threshold)")
    ap.add_argument("--slo-tpot", type=float,
                    default=float(os.environ.get("ISTPU_SLO_TPOT_S", 0.25)),
                    help="TPOT SLO in seconds (goodput threshold)")
    ap.add_argument("--cooldown", type=float, default=0.5,
                    help="seconds between rate points (stragglers drain)")
    ap.add_argument("--warmup", type=int, default=2,
                    help="sequential requests before the sweep so jit "
                         "compilation doesn't pollute the first rate "
                         "point (0 disables)")
    ap.add_argument("--json-out", default=None, metavar="FILE",
                    help="write the run record (run_id + goodput curve; "
                         "docs/observability.md schema)")
    args = ap.parse_args(argv)

    modes = sum(map(bool, (args.url, args.self_serve, args.self_disagg)))
    if modes != 1:
        ap.error("pass exactly one of --url/--target, --self-serve, "
                 "or --self-disagg")
    srv = None
    fleet_close = None
    fleet_workers = None
    url = args.url
    vocab = args.vocab
    if args.self_serve:
        srv, url, model_vocab = self_serve(args)
        vocab = min(vocab, model_vocab)
    elif args.self_disagg:
        fleet_close, url, model_vocab, fleet_workers = self_disagg(args)
        vocab = min(vocab, model_vocab)
    pacer = None if args.pacer == "auto" else args.pacer
    # the load generator's target: every router replica when the fleet
    # has more than one (clients spread across them round-robin and
    # fail over on connect errors); `url` stays the primary replica for
    # the debug-endpoint gathering below
    urls = url
    if fleet_workers is not None and len(fleet_workers.get("router", ())) > 1:
        urls = [f"http://127.0.0.1:{r.port}"
                for r in fleet_workers["router"]]
    base = LoadConfig(
        rate=args.rates[0], n_requests=args.n, process=args.process,
        seed=args.seed, mix=args.mix, lanes=args.lanes,
        n_prefixes=args.prefixes, prefix_len=args.prefix_len,
        prefix_frac=args.prefix_frac, vocab=vocab,
        stream=not args.no_stream, timeout_s=args.timeout,
        honor_retry_after=args.honor_retry_after,
    )

    def show(point):
        lanes = "  ".join(
            f"lane {k}: ttft p50/p99 "
            f"{(v['ttft'] or {}).get('p50_ms', '-')}/"
            f"{(v['ttft'] or {}).get('p99_ms', '-')} ms"
            for k, v in point["lanes"].items()
        )
        print(
            f"# rate {point['offered_rate_rps']:>6.2f} rps  "
            f"completed {point['completed']}/{point['n']}  "
            f"rejected {point.get('rejected', 0)}  "
            f"goodput {point['goodput_rps']:.2f} rps  "
            f"attainment {point['slo_attainment']:.0%}  {lanes}",
            file=sys.stderr,
        )

    t0 = time.time()
    disagg = None
    try:
        if args.warmup:
            from dataclasses import replace

            from infinistore_tpu.loadgen import _http_post, make_requests

            for body in make_requests(
                replace(base, n_requests=args.warmup, seed=base.seed - 1)
            ):
                r = _http_post(url, body, args.timeout)
                if not r["ok"]:
                    print(f"# warmup request failed: {r['error']}",
                          file=sys.stderr)
        if args.conversation:
            # conversation sweep: open-loop SESSION arrivals per rate
            # point, each point summarized like a load point (same
            # lanes/goodput math over the per-turn results) PLUS the
            # per-turn contract numbers from session_summary
            from infinistore_tpu.loadgen import (SessionConfig,
                                                 run_sessions,
                                                 session_summary,
                                                 summarize)

            curve = []
            for i, rate in enumerate(args.rates):
                scfg = SessionConfig(
                    rate=float(rate), n_sessions=args.sessions,
                    process=args.process, seed=args.seed + i,
                    turns=args.turns, think_s=args.think,
                    system_prompt_len=args.system_prompt_len,
                    turn_tokens=args.turn_tokens,
                    max_tokens=args.conv_max_tokens, lanes=args.lanes,
                    vocab=vocab, stream=not args.no_stream,
                    timeout_s=args.timeout,
                )
                results, makespan = run_sessions(urls, scfg, pacer=pacer)
                point = summarize(results, makespan, args.slo_ttft,
                                  args.slo_tpot, rate=float(rate))
                point["sessions"] = session_summary(results)
                curve.append(point)
                show(point)
                if args.cooldown and rate != args.rates[-1]:
                    time.sleep(args.cooldown)
        else:
            curve = sweep(urls, base, args.rates, args.slo_ttft,
                          args.slo_tpot, cooldown_s=args.cooldown,
                          on_point=show, pacer=pacer)
        # the step profiler's summary for the whole sweep (best-effort:
        # older servers have no /debug/engine) — host-stall share,
        # retrace pressure, dispatch counts next to the goodput curve
        stepprof = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/engine?limit=0",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                stepprof = payload.get("summary")
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        # the health plane's verdict on the run (best-effort, same
        # contract): alert firing transitions observed during the sweep
        # and the peak burn rate the watchdogs saw — a load point that
        # pages is a different result than one that merely misses SLO
        health = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/health",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                alerts = payload.get("alerts") or {}
                burn_peaks = [
                    a.get("peak") or 0.0 for name, a in alerts.items()
                    if name.endswith("_burn")
                ]
                health = {
                    "alerts_fired": payload.get("alerts_fired", 0),
                    "firing": payload.get("firing", []),
                    "burn_rate_peak": round(max(burn_peaks, default=0.0),
                                            3),
                    "alerts": {
                        name: {"fired": a.get("fired", 0),
                               "peak": a.get("peak")}
                        for name, a in alerts.items() if a.get("fired")
                    },
                }
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        # the admission plane's verdict (best-effort, same contract):
        # server-side shed/quota tallies next to the client-observed
        # rejection counts below
        admission_dbg = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/admission",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                admission_dbg = payload
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        # the usage ledger's verdict (best-effort, same contract):
        # per-tenant occupancy vs tokens-saved as /debug/usage joins it
        usage_dbg = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/usage",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                usage_dbg = payload
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        # the session ledger's verdict (best-effort, same contract):
        # lifetime waste/computed totals from /debug/sessions — against
        # a fleet the decode workers hold the ledgers, so aggregate
        # their endpoints too; the front door itself answers the
        # affinity tallies via /debug/fleet
        sessions_dbg = []
        sess_targets = [url]
        for s in (fleet_workers or {}).get("decode", ()):
            sess_targets.append(f"http://127.0.0.1:{s.port}")
        for tgt in sess_targets:
            try:
                import urllib.request

                with urllib.request.urlopen(tgt + "/debug/sessions",
                                            timeout=5) as r:
                    payload = json.loads(r.read())
                if payload.get("enabled"):
                    sessions_dbg.append(payload)
            except Exception:  # noqa: BLE001 — observability, not the bench
                pass
        fleet_sessions = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/fleet",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                fleet_sessions = payload.get("sessions")
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        # the reshape plane's verdict (best-effort, same contract):
        # if the store ring behind the server migrated during the run,
        # /debug/cluster carries the last migration's throughput
        cluster_dbg = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/cluster",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                cluster_dbg = payload
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        # the stage ledger's verdict (best-effort, same contract): the
        # canonical TTFT decomposition at sweep end — /debug/critpath
        # answers worker-grain on a monolith and router-grain against a
        # fleet with the same shape, so two captures are diffable by
        # scripts/trace_diff.py either way
        critpath_dbg = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/critpath?limit=0",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                critpath_dbg = payload
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        # the resumption plane's fleet-side half (best-effort, same
        # contract): the router-merged stream ledger ("did any stream
        # die?" — aborts + resumes summed across replicas) and the
        # decode workers' checkpoint-overhead counters
        fleet_merged = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/fleet?merged=1",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                fleet_merged = payload
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        ckpt_writes = ckpt_tokens = 0.0
        ckpt_seen = False
        for s in (fleet_workers or {}).get("decode", ()):
            try:
                import urllib.request

                from infinistore_tpu.utils.metrics import \
                    parse_prometheus_text

                with urllib.request.urlopen(
                        f"http://127.0.0.1:{s.port}/metrics",
                        timeout=5) as r:
                    fams = parse_prometheus_text(r.read().decode())
            except Exception:  # noqa: BLE001
                continue
            for (name, _labels), v in fams.items():
                if name == "istpu_serve_resume_ckpt_writes_total":
                    ckpt_writes += v
                    ckpt_seen = True
                elif name == "istpu_serve_resume_ckpt_tokens_total":
                    ckpt_tokens += v
                    ckpt_seen = True
        disagg = None
        if args.self_disagg:
            disagg = _gather_disagg(url, fleet_workers, args)
    finally:
        if srv is not None:
            srv.close()
        if fleet_close is not None:
            fleet_close()
    # the same-budget monolith comparison: one server whose max_batch
    # equals the decode pool's total (equal decode throughput), swept on
    # the SAME schedule AFTER the fleet is torn down (fresh server, no
    # CPU contention between the two measurements)
    if disagg is not None and not args.no_monolith_baseline:
        import argparse as _argparse
        from dataclasses import replace

        from infinistore_tpu.loadgen import _http_post, make_requests

        mono_args = _argparse.Namespace(**{
            **vars(args),
            "self_serve_batch":
                args.self_serve_batch * max(1, args.decode_workers),
            "quotas": [],
        })
        msrv, murl, _mv = self_serve(mono_args)
        try:
            if args.warmup:
                for body in make_requests(
                    replace(base, n_requests=args.warmup,
                            seed=base.seed - 1)
                ):
                    _http_post(murl, body, args.timeout)
            mono_curve = sweep(murl, base, args.rates, args.slo_ttft,
                               args.slo_tpot, cooldown_s=args.cooldown)
        finally:
            msrv.close()
        top, mtop = curve[-1], mono_curve[-1]
        d_ttft = _lane_pct(top, "ttft", "p99_ms")
        m_ttft = _lane_pct(mtop, "ttft", "p99_ms")
        d_tpot = _lane_pct(top, "tpot", "p99_ms")
        m_tpot = _lane_pct(mtop, "tpot", "p99_ms")
        disagg["ttft_p99_ms"] = {"disagg": d_ttft, "monolith": m_ttft}
        disagg["tpot_p99_ms"] = {"disagg": d_tpot, "monolith": m_tpot}
        disagg["monolith_curve"] = mono_curve
        if d_ttft and m_ttft:
            disagg["ttft_ratio"] = round(d_ttft / m_ttft, 4)
        if d_tpot and m_tpot:
            disagg["tpot_burst_ratio"] = round(d_tpot / m_tpot, 4)
    record = {
        "run_id": uuid.uuid4().hex[:8],
        "kind": "serve_load",
        "slo": {"ttft_s": args.slo_ttft, "tpot_s": args.slo_tpot},
        "config": {
            "n_per_rate": args.n, "process": args.process,
            "mix": [list(m) for m in args.mix],
            "lanes": [list(p) for p in args.lanes],
            "prefixes": args.prefixes, "prefix_len": args.prefix_len,
            "prefix_frac": args.prefix_frac, "stream": not args.no_stream,
        },
        "wall_s": round(time.time() - t0, 1),
        "curve": curve,
    }
    if stepprof is not None:
        # profiler summary block (engine/stepprof.py): joins the schema
        # the same way `slo`/`config` do — stable keys, documented in
        # docs/observability.md §engine-attribution
        record["stepprof"] = stepprof
        # dispatch-economy mirrors for the trend table
        # (scripts/bench_history.py): compiled programs per decoded
        # token over the whole sweep (down is good) and accepted spec
        # tokens per fused dispatch (up is good; absent when the server
        # never speculated)
        if stepprof.get("dispatches_per_token") is not None:
            record["dispatches_per_token"] = \
                stepprof["dispatches_per_token"]
        if stepprof.get("spec_accept_per_dispatch") is not None:
            record["spec_accept_per_dispatch"] = \
                stepprof["spec_accept_per_dispatch"]
    # admission block (docs/observability.md): shed counts per lane as
    # the CLIENT saw them (429s per priority lane), the server-side
    # shed/quota tallies when /debug/admission answered, and the
    # plateau flag — did goodput at the highest offered rate hold ≥50%
    # of the curve's peak (a plateau) instead of collapsing?
    per_lane_shed: dict = {}
    for pt in curve:
        for lane, v in pt["lanes"].items():
            per_lane_shed[lane] = (per_lane_shed.get(lane, 0)
                                   + (v.get("rejected") or 0))
    goodputs = [p["goodput_rps"] for p in curve]
    plateau = bool(len(goodputs) >= 2 and max(goodputs) > 0
                   and goodputs[-1] >= 0.5 * max(goodputs))
    record["admission"] = {
        "rejected_total": sum(p.get("rejected", 0) for p in curve),
        "per_lane_shed": per_lane_shed,
        "plateau": plateau,
    }
    if admission_dbg is not None:
        record["admission"]["server"] = {
            "mode": admission_dbg.get("mode"),
            "shed_total": admission_dbg.get("shed_total"),
            "shed_by_reason": admission_dbg.get("shed_by_reason"),
            "quota_throttled": (admission_dbg.get("quota")
                                or {}).get("throttled_total"),
        }
    # mirrored top-level (0/1) for the scripts/bench_history.py trend
    # table: an overload round whose plateau flag drops to 0 regressed
    record["goodput_plateau"] = int(plateau)
    # resumption block (docs/observability.md §Resumption): the
    # client-observed splice ledger over the whole sweep (resumed =
    # streams that crossed at least one splice, stalled = the same
    # requests as the client's stall accounting sees them, max_stall_ms
    # = the worst client-visible gap), the router-merged server-side
    # view when a fleet answered /debug/fleet?merged=1, and the decode
    # pool's checkpoint-overhead counters.  stream_resumes mirrors
    # top-level for scripts/bench_history.py (direction: down — a quiet
    # fleet resumes nothing)
    resumption = {
        "resumed": sum(p.get("resumed") or 0 for p in curve),
        "stalled": sum(p.get("stalled") or 0 for p in curve),
        "max_stall_ms": max(
            (p.get("max_stall_ms") for p in curve
             if p.get("max_stall_ms") is not None), default=None),
        "routers": args.routers if args.self_disagg else None,
    }
    if fleet_merged is not None:
        resumption["fleet"] = {
            "replicas": fleet_merged.get("replicas"),
            "reachable": fleet_merged.get("reachable"),
            "stream": fleet_merged.get("stream"),
        }
    if ckpt_seen:
        resumption["checkpoint"] = {
            "writes": ckpt_writes, "tokens": ckpt_tokens,
        }
    record["resumption"] = resumption
    record["stream_resumes"] = resumption["resumed"]
    if resumption["max_stall_ms"] is not None:
        record["max_stall_ms"] = resumption["max_stall_ms"]
    if args.conversation:
        # sessions block (docs/observability.md §Session attribution):
        # the persistence-contract numbers for the run — the fraction of
        # computed prompt tokens that were re-prefill waste (down is
        # good; a warm store holds it ~0), the session-affinity hit rate
        # among RE-visits (up is good; fallback is every session's first
        # placement, not a miss), and the client-observed per-turn TTFT
        # slope at the top offered rate — with the first two mirrored
        # top-level for scripts/bench_history.py
        record["config"]["conversation"] = {
            "sessions_per_rate": args.sessions,
            "turns": [list(t) for t in args.turns],
            "turn_tokens": [list(t) for t in args.turn_tokens],
            "system_prompt_len": args.system_prompt_len,
            "think_s": list(args.think),
            "max_tokens": args.conv_max_tokens,
        }
        sess_block = {
            "per_turn": (curve[-1].get("sessions") or {}).get("per_turn"),
            "ttft_slope_ms_per_turn":
                (curve[-1].get("sessions") or {})
                .get("ttft_slope_ms_per_turn"),
        }
        if sessions_dbg:
            waste = sum((p.get("totals") or {}).get("waste_tokens", 0)
                        for p in sessions_dbg)
            computed = sum(
                (p.get("totals") or {}).get("computed_tokens", 0)
                for p in sessions_dbg)
            sess_block["waste_tokens"] = waste
            sess_block["computed_tokens"] = computed
            sess_block["reprefill_waste_frac"] = (
                round(waste / computed, 4) if computed else 0.0)
            record["reprefill_waste_frac"] = \
                sess_block["reprefill_waste_frac"]
        if fleet_sessions is not None:
            aff = fleet_sessions.get("affinity") or {}
            sess_block["affinity"] = aff
            revisits = (aff.get("hit") or 0) + (aff.get("miss") or 0)
            if revisits:
                sess_block["affinity_hit_rate"] = round(
                    (aff.get("hit") or 0) / revisits, 4)
                record["affinity_hit_rate"] = \
                    sess_block["affinity_hit_rate"]
        record["sessions"] = sess_block
    if disagg is not None:
        # disaggregation block (docs/observability.md): per-role worker
        # counts, handoff leg percentiles, decode-pool adoption hit
        # rate, and the TTFT/TPOT-vs-monolith ratios at the top offered
        # rate — the headline ratios mirror top-level for
        # scripts/bench_history.py (direction: down; < 1.0 means the
        # fleet beat the same-decode-budget monolith)
        record["disagg"] = disagg
        if disagg.get("ttft_ratio") is not None:
            record["ttft_ratio"] = disagg["ttft_ratio"]
        if disagg.get("tpot_burst_ratio") is not None:
            record["tpot_burst_ratio"] = disagg["tpot_burst_ratio"]
    if usage_dbg is not None:
        # usage block (docs/observability.md §Usage attribution): the
        # per-tenant ledger at sweep end — occupancy byte·seconds, token
        # provenance, economics — with the fleet-wide reuse ratio
        # mirrored top-level for scripts/bench_history.py (up is good:
        # more prompt tokens served from the store per byte held)
        tenants = usage_dbg.get("tenants") or {}
        tok_store = sum((t.get("tokens") or {}).get("store", 0.0)
                       for t in tenants.values())
        tok_all = sum(sum((t.get("tokens") or {}).values())
                      for t in tenants.values())
        record["usage"] = {
            "tenants": tenants,
            "top_occupants": usage_dbg.get("top_occupants"),
            "top_savers": usage_dbg.get("top_savers"),
            "doa_offenders": usage_dbg.get("doa_offenders"),
            "nodes": usage_dbg.get("nodes"),
        }
        if tok_all:
            record["usage_reuse_ratio"] = round(tok_store / tok_all, 4)
    if health is not None:
        # health-plane block (infinistore_tpu/health.py): alert
        # transitions + burn-rate peak during the run.  alerts_fired is
        # ALSO mirrored top-level so scripts/bench_history.py trends it
        # (direction: down) without digging into nested blocks
        record["health"] = health
        record["alerts_fired"] = health["alerts_fired"]
        record["burn_rate_peak"] = health["burn_rate_peak"]
    if cluster_dbg is not None:
        # reshape throughput mirrored top-level for the trend table
        # (up is good) — only when a migration actually ran: a sweep
        # with no membership change emits no row, and bench_history
        # skips absent keys
        mig = cluster_dbg.get("migration") or {}
        if mig.get("migrate_gbps") is not None:
            record["migrate_gbps"] = mig["migrate_gbps"]
    if critpath_dbg is not None:
        # critpath block (docs/observability.md §Latency attribution):
        # the per-stage TTFT decomposition at sweep end, row tail
        # dropped (the aggregates are the diffable artifact).  Each
        # stage's p99 mirrors top-level as stage_p99_<stage>_ms so
        # scripts/bench_history.py trends the decomposition and
        # scripts/trace_diff.py names a regressed stage from two of
        # these captures
        overall = critpath_dbg.get("overall") or {}
        record["critpath"] = {
            "role": critpath_dbg.get("role"),
            "stages": critpath_dbg.get("stages"),
            "overall": overall,
            "lanes": critpath_dbg.get("lanes"),
        }
        for s, v in (overall.get("stage_p99_ms") or {}).items():
            record[f"stage_p99_{s}_ms"] = v
    print(json.dumps(record))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
