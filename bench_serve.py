"""Serving SLO benchmark: open-loop goodput-vs-rate curve.

Sweeps arrival rates against a live serving front-end (or a self-hosted
tiny-model server with ``--self-serve``) through the open-loop harness
(`infinistore_tpu/loadgen.py`): Poisson/deterministic arrivals,
concurrent streaming sessions, a shared-prefix request population, and
per-lane TTFT/TPOT percentiles.  The headline output is **goodput** —
requests/s that complete AND meet the TTFT+TPOT SLOs — per offered
rate, the curve ROADMAP item 4's admission/QoS work will be judged
against.

    # against a running server
    python bench_serve.py --url http://127.0.0.1:8000 --rates 2,4,8 \
        --n 64 --slo-ttft 2.0 --slo-tpot 0.25 --json-out serve_load.json

    # zero-setup smoke (in-process tiny model; CI uses this)
    JAX_PLATFORMS=cpu python bench_serve.py --self-serve --rates 8,16 --n 24

``--json-out`` writes one JSON object joining the bench-schema family
(``run_id`` + stable keys; docs/observability.md): ``{run_id, kind:
"serve_load", slo: {...}, config: {...}, curve: [per-rate summaries],
stepprof: {...}, health: {...}}`` — ``stepprof`` is the server's
step-profiler summary (``GET /debug/engine``): host-stall share,
retrace pressure, dispatch counts for the whole sweep; ``health`` is
the health plane's verdict (``GET /debug/health``): alert firing
transitions and the peak burn rate observed, with ``alerts_fired``
mirrored top-level for the trend table (both absent against servers
without the endpoints); ``admission`` is the overload-control verdict —
client-observed 429 shed counts per lane, the server's
``GET /debug/admission`` shed/quota tallies, and the ``plateau`` flag
(goodput at the highest offered rate held ≥50% of the curve's peak
instead of collapsing), with ``goodput_plateau`` mirrored top-level.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import uuid

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def parse_rates(s: str):
    rates = [float(x) for x in s.split(",") if x.strip()]
    if not rates:
        raise argparse.ArgumentTypeError("need at least one rate")
    return rates


def parse_mix(s: str):
    """``weight:prompt:max_tokens`` triples, comma-separated — e.g.
    ``3:24:8,1:96:32`` = 3/4 short chat turns, 1/4 long generations."""
    mix = []
    for part in s.split(","):
        w, p, m = part.split(":")
        mix.append((float(w), int(p), int(m)))
    return mix


def parse_lanes(s: str):
    """``priority:weight`` pairs, comma-separated — e.g. ``10:1,0:4`` =
    1 in 5 requests rides the high-priority lane."""
    lanes = []
    for part in s.split(","):
        prio, w = part.split(":")
        lanes.append((int(prio), float(w)))
    return lanes


def self_serve(args):
    """An in-process tiny-model ServingServer on a free port: the
    zero-setup target for smokes — real HTTP, real scheduler, no
    checkpoint or separate process needed."""
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from infinistore_tpu.engine import InferenceEngine
    from infinistore_tpu.kv import PagedCacheConfig
    from infinistore_tpu.models import TINY, init_params, scaled
    from infinistore_tpu.serve import ServingServer

    cfg = scaled(TINY, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    pc = PagedCacheConfig(
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, n_blocks=args.self_serve_blocks,
        block_tokens=4, dtype=cfg.dtype,
    )
    eng = InferenceEngine(params, cfg, pc)
    eng.decode_chunk = 4
    srv = ServingServer(eng, port=0, max_batch=args.self_serve_batch,
                        model_id="tiny-bench",
                        slo_ttft_s=args.slo_ttft, slo_tpot_s=args.slo_tpot,
                        quotas=args.quotas or None)
    srv.start()
    return srv, f"http://127.0.0.1:{srv.port}", cfg.vocab_size


def main(argv=None) -> int:
    from infinistore_tpu.loadgen import LoadConfig, sweep

    ap = argparse.ArgumentParser("bench_serve.py")
    ap.add_argument("--url", default=None,
                    help="serving front-end base URL (http://host:8000)")
    ap.add_argument("--self-serve", action="store_true",
                    help="spin up an in-process tiny-model server to "
                         "load instead of --url (CI smoke mode)")
    ap.add_argument("--self-serve-blocks", type=int, default=512)
    ap.add_argument("--self-serve-batch", type=int, default=8)
    ap.add_argument("--rates", type=parse_rates, default=[2.0, 4.0, 8.0],
                    help="comma-separated arrival rates (req/s) to sweep")
    ap.add_argument("--n", type=int, default=32,
                    help="requests per rate point")
    ap.add_argument("--process", choices=["poisson", "deterministic"],
                    default="poisson")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mix", type=parse_mix, default=[(1.0, 24, 8)],
                    help="weight:prompt_tokens:max_tokens triples, "
                         "comma-separated (default 1:24:8)")
    ap.add_argument("--lanes", type=parse_lanes, default=[(0, 1.0)],
                    help="priority:weight pairs, comma-separated "
                         "(default 0:1 — one lane)")
    ap.add_argument("--prefixes", type=int, default=4,
                    help="shared-prefix population size (0 disables)")
    ap.add_argument("--prefix-len", type=int, default=16)
    ap.add_argument("--prefix-frac", type=float, default=0.5,
                    help="fraction of requests that prepend a shared "
                         "prefix (tenant system-prompt traffic shape)")
    ap.add_argument("--vocab", type=int, default=256,
                    help="token ids drawn in [0, vocab) — keep within "
                         "the served model's vocab")
    ap.add_argument("--no-stream", action="store_true",
                    help="non-streaming requests (TTFT == e2e)")
    ap.add_argument("--honor-retry-after", action="store_true",
                    help="a 429-shed request sleeps the server's "
                         "Retry-After (capped 10 s) and re-attempts "
                         "once; default off — the raw shed behavior is "
                         "the measurement")
    ap.add_argument("--quota", action="append", default=[],
                    dest="quotas", metavar="TENANT:TOKS_PER_S[:BURST_S]",
                    help="--self-serve only: per-tenant token quotas "
                         "passed through to the in-process server")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--slo-ttft", type=float,
                    default=float(os.environ.get("ISTPU_SLO_TTFT_S", 2.0)),
                    help="TTFT SLO in seconds (goodput threshold)")
    ap.add_argument("--slo-tpot", type=float,
                    default=float(os.environ.get("ISTPU_SLO_TPOT_S", 0.25)),
                    help="TPOT SLO in seconds (goodput threshold)")
    ap.add_argument("--cooldown", type=float, default=0.5,
                    help="seconds between rate points (stragglers drain)")
    ap.add_argument("--warmup", type=int, default=2,
                    help="sequential requests before the sweep so jit "
                         "compilation doesn't pollute the first rate "
                         "point (0 disables)")
    ap.add_argument("--json-out", default=None, metavar="FILE",
                    help="write the run record (run_id + goodput curve; "
                         "docs/observability.md schema)")
    args = ap.parse_args(argv)

    if bool(args.url) == bool(args.self_serve):
        ap.error("pass exactly one of --url or --self-serve")
    srv = None
    url = args.url
    vocab = args.vocab
    if args.self_serve:
        srv, url, model_vocab = self_serve(args)
        vocab = min(vocab, model_vocab)
    base = LoadConfig(
        rate=args.rates[0], n_requests=args.n, process=args.process,
        seed=args.seed, mix=args.mix, lanes=args.lanes,
        n_prefixes=args.prefixes, prefix_len=args.prefix_len,
        prefix_frac=args.prefix_frac, vocab=vocab,
        stream=not args.no_stream, timeout_s=args.timeout,
        honor_retry_after=args.honor_retry_after,
    )

    def show(point):
        lanes = "  ".join(
            f"lane {k}: ttft p50/p99 "
            f"{(v['ttft'] or {}).get('p50_ms', '-')}/"
            f"{(v['ttft'] or {}).get('p99_ms', '-')} ms"
            for k, v in point["lanes"].items()
        )
        print(
            f"# rate {point['offered_rate_rps']:>6.2f} rps  "
            f"completed {point['completed']}/{point['n']}  "
            f"rejected {point.get('rejected', 0)}  "
            f"goodput {point['goodput_rps']:.2f} rps  "
            f"attainment {point['slo_attainment']:.0%}  {lanes}",
            file=sys.stderr,
        )

    t0 = time.time()
    try:
        if args.warmup:
            from dataclasses import replace

            from infinistore_tpu.loadgen import _http_post, make_requests

            for body in make_requests(
                replace(base, n_requests=args.warmup, seed=base.seed - 1)
            ):
                r = _http_post(url, body, args.timeout)
                if not r["ok"]:
                    print(f"# warmup request failed: {r['error']}",
                          file=sys.stderr)
        curve = sweep(url, base, args.rates, args.slo_ttft, args.slo_tpot,
                      cooldown_s=args.cooldown, on_point=show)
        # the step profiler's summary for the whole sweep (best-effort:
        # older servers have no /debug/engine) — host-stall share,
        # retrace pressure, dispatch counts next to the goodput curve
        stepprof = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/engine?limit=0",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                stepprof = payload.get("summary")
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        # the health plane's verdict on the run (best-effort, same
        # contract): alert firing transitions observed during the sweep
        # and the peak burn rate the watchdogs saw — a load point that
        # pages is a different result than one that merely misses SLO
        health = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/health",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                alerts = payload.get("alerts") or {}
                burn_peaks = [
                    a.get("peak") or 0.0 for name, a in alerts.items()
                    if name.endswith("_burn")
                ]
                health = {
                    "alerts_fired": payload.get("alerts_fired", 0),
                    "firing": payload.get("firing", []),
                    "burn_rate_peak": round(max(burn_peaks, default=0.0),
                                            3),
                    "alerts": {
                        name: {"fired": a.get("fired", 0),
                               "peak": a.get("peak")}
                        for name, a in alerts.items() if a.get("fired")
                    },
                }
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
        # the admission plane's verdict (best-effort, same contract):
        # server-side shed/quota tallies next to the client-observed
        # rejection counts below
        admission_dbg = None
        try:
            import urllib.request

            with urllib.request.urlopen(url + "/debug/admission",
                                        timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("enabled"):
                admission_dbg = payload
        except Exception:  # noqa: BLE001 — observability, not the bench
            pass
    finally:
        if srv is not None:
            srv.close()
    record = {
        "run_id": uuid.uuid4().hex[:8],
        "kind": "serve_load",
        "slo": {"ttft_s": args.slo_ttft, "tpot_s": args.slo_tpot},
        "config": {
            "n_per_rate": args.n, "process": args.process,
            "mix": [list(m) for m in args.mix],
            "lanes": [list(p) for p in args.lanes],
            "prefixes": args.prefixes, "prefix_len": args.prefix_len,
            "prefix_frac": args.prefix_frac, "stream": not args.no_stream,
        },
        "wall_s": round(time.time() - t0, 1),
        "curve": curve,
    }
    if stepprof is not None:
        # profiler summary block (engine/stepprof.py): joins the schema
        # the same way `slo`/`config` do — stable keys, documented in
        # docs/observability.md §engine-attribution
        record["stepprof"] = stepprof
        # dispatch-economy mirrors for the trend table
        # (scripts/bench_history.py): compiled programs per decoded
        # token over the whole sweep (down is good) and accepted spec
        # tokens per fused dispatch (up is good; absent when the server
        # never speculated)
        if stepprof.get("dispatches_per_token") is not None:
            record["dispatches_per_token"] = \
                stepprof["dispatches_per_token"]
        if stepprof.get("spec_accept_per_dispatch") is not None:
            record["spec_accept_per_dispatch"] = \
                stepprof["spec_accept_per_dispatch"]
    # admission block (docs/observability.md): shed counts per lane as
    # the CLIENT saw them (429s per priority lane), the server-side
    # shed/quota tallies when /debug/admission answered, and the
    # plateau flag — did goodput at the highest offered rate hold ≥50%
    # of the curve's peak (a plateau) instead of collapsing?
    per_lane_shed: dict = {}
    for pt in curve:
        for lane, v in pt["lanes"].items():
            per_lane_shed[lane] = (per_lane_shed.get(lane, 0)
                                   + (v.get("rejected") or 0))
    goodputs = [p["goodput_rps"] for p in curve]
    plateau = bool(len(goodputs) >= 2 and max(goodputs) > 0
                   and goodputs[-1] >= 0.5 * max(goodputs))
    record["admission"] = {
        "rejected_total": sum(p.get("rejected", 0) for p in curve),
        "per_lane_shed": per_lane_shed,
        "plateau": plateau,
    }
    if admission_dbg is not None:
        record["admission"]["server"] = {
            "mode": admission_dbg.get("mode"),
            "shed_total": admission_dbg.get("shed_total"),
            "shed_by_reason": admission_dbg.get("shed_by_reason"),
            "quota_throttled": (admission_dbg.get("quota")
                                or {}).get("throttled_total"),
        }
    # mirrored top-level (0/1) for the scripts/bench_history.py trend
    # table: an overload round whose plateau flag drops to 0 regressed
    record["goodput_plateau"] = int(plateau)
    if health is not None:
        # health-plane block (infinistore_tpu/health.py): alert
        # transitions + burn-rate peak during the run.  alerts_fired is
        # ALSO mirrored top-level so scripts/bench_history.py trends it
        # (direction: down) without digging into nested blocks
        record["health"] = health
        record["alerts_fired"] = health["alerts_fired"]
        record["burn_rate_peak"] = health["burn_rate_peak"]
    print(json.dumps(record))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
