#!/usr/bin/env bash
# One-command on-chip evidence battery, priority-ordered for a tunnel
# that may wedge again at any moment (it was down for all of round 5).
# Run the INSTANT a probe answers:
#
#     ./scripts/chip_evidence.sh            # everything, ~25-35 min
#     ./scripts/chip_evidence.sh quick      # bench only, ~20 min
#
# Order rationale:
#  1. bench_tpu.py FIRST — it carries every round-5 question (ngram +
#     distilled spec speedups, invocation overhead, prefill breakdown,
#     relaxed-durability store overhead, flash 2k/8k median-of-3) and
#     auto-refreshes BENCH_TPU_SNAPSHOT.json on a healthy run, so even
#     a re-wedge preserves the capture;
#  2. Mosaic acceptance (the reshaped shared kernel body + the new
#     all-layers instrument need real-Mosaic validation);
#  3. the full suite stays OFF this path (CPU-only, run separately).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== probe =="
if ! timeout 120 python -c "
import jax, numpy as np
x = jax.device_put(np.ones((256, 256), np.float32))
assert float(np.asarray(x @ x)[0, 0]) == 256.0
print('tunnel alive:', jax.devices()[0].device_kind)"; then
    echo "tunnel not answering; try again later" >&2
    exit 1
fi

echo "== bench_tpu (snapshot auto-refreshes on healthy completion) =="
timeout 2100 python bench_tpu.py | tail -1 | tee /tmp/bench_tpu_last.json

if [[ "${1:-}" == "quick" ]]; then
    exit 0
fi

echo "== Mosaic acceptance =="
timeout 900 env ISTPU_TEST_TPU=1 python -m pytest tests/test_ops.py \
    -k on_tpu -q

echo "== done; remember: git add BENCH_TPU_SNAPSHOT.json && commit =="
