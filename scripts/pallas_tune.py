#!/usr/bin/env python3
"""Pallas kernel tuning sweep: block sizes / layouts vs XLA, on chip.

The decision record (docs/tpu_perf_notes.md): both Pallas attention
kernels ship opt-in-OFF because their in-model measurements lose to XLA
on the tunneled v5e (paged decode 0.69x, flash unreplicated around
1.0x), and the loss pattern points at per-``pallas_call`` invocation
overhead rather than kernel math.  This script is the RE-ENTRY PATH for
the next live TPU capture: one command sweeps the tunable surface —
flash ``block_q``/``block_k`` tiles over the Mosaic acceptance shapes,
the paged-decode kernel (ours and, when requested, jax's bundled
production kernel via the model-layer flag) against XLA across context
lengths — and writes a bench-schema JSON so the verdict is a table, not
an afternoon of ad-hoc timing.

    # on a TPU host
    python scripts/pallas_tune.py --json-out pallas_tune.json

    # CPU structural smoke (interpret mode, tiny shapes — validates the
    # sweep plumbing, NOT kernel performance)
    JAX_PLATFORMS=cpu python scripts/pallas_tune.py --force --json-out t.json

Methodology follows the platform traps (docs/tpu_perf_notes.md): timed
regions chain iterations through evolving inputs (defeats dispatch
memoization) and end in a data fetch (defeats optimistic
``block_until_ready``); every timing is median-of-N with the relative
spread recorded next to it.  Without a TPU (and without ``--force``)
the script emits a stub record and exits 0 — a dead tunnel must not
look like a kernel regression.

Output schema (``--json-out``, bench family; docs/observability.md
§bench-json): ``{run_id, kind: "pallas_tune", platform, device_kind,
tpu, flash: [{block_q, block_k, shape, t_ms, spread, vs_xla}],
decode: [{ctx, kernel, t_ms, spread, vs_xla}], best: {...}}`` —
``vs_xla > 1`` means the kernel beat XLA at that point; ``best``
summarizes the winning config per family, the number the
``pallas_speedup_vs_xla`` staged assert (bench_tpu.py) settles on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import uuid

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _median_spread(measure, n: int):
    vals = sorted(measure() for _ in range(max(1, n)))
    med = vals[len(vals) // 2]
    spread = (vals[-1] - vals[0]) / med if med > 0 else 0.0
    return med, round(spread, 3)


def _fetch(x) -> float:
    """Ground-truth sync: pull a scalar reduction to the host —
    ``block_until_ready`` can return early on the tunneled runtime."""
    import jax.numpy as jnp

    return float(jnp.sum(x.astype(jnp.float32)))


def _time_chained(step, x0, iters: int) -> float:
    """Seconds/iteration of ``x = step(x)``: the chain defeats dispatch
    memoization, the final fetch defeats optimistic completion."""
    x = step(x0)  # warm (compile)
    _fetch(x)
    t0 = time.perf_counter()
    for _ in range(iters):
        x = step(x)
    _fetch(x)
    return (time.perf_counter() - t0) / iters


def sweep_flash(interpret: bool, small: bool, iters: int, repeats: int):
    """Flash causal prefill: (block_q, block_k) tile sweep vs XLA at the
    Mosaic acceptance shape (B=1, S=512, H=32, Hkv=8, D=128) and a 2k
    long-prompt point."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from infinistore_tpu.models.attention import causal_attention
    from infinistore_tpu.ops import flash_causal_attention_pallas

    rng = np.random.default_rng(0)
    shapes = [(1, 128, 4, 2, 128)] if small else [
        (1, 512, 32, 8, 128),   # the Mosaic acceptance shape
        (1, 2048, 32, 8, 128),  # long-prompt point (r5 flash leg shape)
    ]
    blocks = [(128, 128)] if small else [
        (128, 128), (256, 128), (128, 256), (256, 256), (512, 256),
    ]
    dtype = jnp.float32 if small else jnp.bfloat16
    results = []
    for B, S, H, Hkv, D in shapes:
        q = jnp.asarray(rng.standard_normal((B, S, H, D)), dtype)
        k = jnp.asarray(rng.standard_normal((B, S, Hkv, D)), dtype)
        v = jnp.asarray(rng.standard_normal((B, S, Hkv, D)), dtype)

        def xla_step(x):
            return causal_attention(q + x[0, 0, 0, 0] * 1e-6, k, v,
                                    allow_pallas=False)

        t_xla, sp_xla = _median_spread(
            lambda: _time_chained(xla_step, q, iters), repeats)
        for bq, bk in blocks:
            if bq > S:
                continue

            def pl_step(x, _bq=bq, _bk=bk):
                return flash_causal_attention_pallas(
                    q + x[0, 0, 0, 0] * 1e-6, k, v,
                    block_q=_bq, block_k=_bk, interpret=interpret)

            try:
                t_pl, sp_pl = _median_spread(
                    lambda: _time_chained(pl_step, q, iters), repeats)
            except Exception as e:  # noqa: BLE001 — Mosaic rejection is data
                results.append({
                    "shape": [B, S, H, Hkv, D], "block_q": bq,
                    "block_k": bk, "error": repr(e)[:160],
                })
                continue
            results.append({
                "shape": [B, S, H, Hkv, D], "block_q": bq, "block_k": bk,
                "t_ms": round(t_pl * 1e3, 3), "spread": sp_pl,
                "xla_t_ms": round(t_xla * 1e3, 3), "xla_spread": sp_xla,
                "vs_xla": round(t_xla / t_pl, 3) if t_pl > 0 else None,
            })
    return results


def sweep_decode(interpret: bool, small: bool, iters: int, repeats: int):
    """Paged decode attention: our kernel (and jax's bundled one where
    available on chip) vs XLA across context lengths at the serving
    head config (Hkv=8, D=128, T=16, B=4)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from infinistore_tpu.models.attention import paged_decode_attention_xla
    from infinistore_tpu.ops import paged_decode_attention_pallas

    rng = np.random.default_rng(1)
    Hkv, D, T = (2, 128, 16) if small else (8, 128, 16)
    H = Hkv * 4
    B = 2 if small else 4
    ctxs = [32] if small else [64, 512, 1536]
    results = []
    for ctx in ctxs:
        n_pages = -(-ctx // T)
        n_blocks = B * n_pages + 1
        q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
        cache = jnp.asarray(
            rng.standard_normal((2, Hkv, n_blocks, T, D)), jnp.float32)
        table = np.zeros((B, n_pages), np.int32)
        for b in range(B):
            table[b] = np.arange(1 + b * n_pages, 1 + (b + 1) * n_pages)
        table = jnp.asarray(table)
        lens = jnp.full((B,), ctx, jnp.int32)

        def xla_step(x):
            return paged_decode_attention_xla(
                q + x[0, 0, 0] * 1e-6, cache, table, lens)

        def pl_step(x):
            return paged_decode_attention_pallas(
                q + x[0, 0, 0] * 1e-6, cache, table, lens,
                interpret=interpret)

        t_xla, sp_xla = _median_spread(
            lambda: _time_chained(xla_step, q, iters), repeats)
        try:
            t_pl, sp_pl = _median_spread(
                lambda: _time_chained(pl_step, q, iters), repeats)
        except Exception as e:  # noqa: BLE001
            results.append({"ctx": ctx, "kernel": "istpu",
                            "error": repr(e)[:160]})
            continue
        results.append({
            "ctx": ctx, "kernel": "istpu",
            "t_ms": round(t_pl * 1e3, 3), "spread": sp_pl,
            "xla_t_ms": round(t_xla * 1e3, 3), "xla_spread": sp_xla,
            "vs_xla": round(t_xla / t_pl, 3) if t_pl > 0 else None,
        })
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("pallas_tune.py")
    ap.add_argument("--json-out", default=None, metavar="FILE")
    ap.add_argument("--iters", type=int, default=20,
                    help="chained iterations per timing")
    ap.add_argument("--repeats", type=int, default=3,
                    help="median-of-N repeats per config")
    ap.add_argument("--force", action="store_true",
                    help="run on whatever backend is present (CPU smoke "
                         "via interpret mode, tiny shapes)")
    args = ap.parse_args(argv)

    import jax

    platform = jax.devices()[0].platform
    record = {
        "run_id": uuid.uuid4().hex[:8],
        "kind": "pallas_tune",
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "tpu": platform == "tpu",
    }
    if platform != "tpu" and not args.force:
        # a dead tunnel is not a kernel verdict: emit the stub and leave
        # rc 0 so drivers record "no capture", never "kernel regressed"
        record["note"] = ("no TPU reachable; re-run on chip (or --force "
                          "for a CPU interpret-mode structural smoke)")
        print(json.dumps(record))
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(record, f, indent=2)
        return 0

    interpret = platform != "tpu"
    small = interpret
    t0 = time.time()
    record["flash"] = sweep_flash(interpret, small, args.iters,
                                  args.repeats)
    record["decode"] = sweep_decode(interpret, small, args.iters,
                                    args.repeats)
    best = {}
    flash_ok = [r for r in record["flash"] if r.get("vs_xla")]
    if flash_ok:
        win = max(flash_ok, key=lambda r: r["vs_xla"])
        best["flash"] = {k: win[k] for k in
                         ("shape", "block_q", "block_k", "vs_xla")}
    dec_ok = [r for r in record["decode"] if r.get("vs_xla")]
    if dec_ok:
        win = max(dec_ok, key=lambda r: r["vs_xla"])
        best["decode"] = {k: win[k] for k in ("ctx", "kernel", "vs_xla")}
        if not interpret:
            # the headline the staged on-chip assert
            # (pallas_speedup_vs_xla >= 1.0) settles on — real-chip
            # numbers only; interpret-mode timings are not kernel perf
            record["pallas_speedup_vs_xla"] = win["vs_xla"]
    record["best"] = best
    record["wall_s"] = round(time.time() - t0, 1)
    if interpret:
        # interpret-mode timings are NOT kernel performance — mark the
        # record so no trend table ever ingests them as such
        record["interpret_smoke"] = True
    print(json.dumps(record))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
