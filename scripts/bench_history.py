#!/usr/bin/env python3
"""Bench-trajectory trend table: join the per-round ``BENCH_r*.json``
driver records (and ``BASELINE.json``'s published numbers, when any)
into one table and flag regressions.

The perf trajectory exists only as loose JSON files nobody reads; this
script is the reader.  Per tracked metric it prints one row across
rounds and compares the LATEST round against the best prior round,
flagging anything that moved the wrong way by more than ``--tolerance``
(default 5%).  Direction-aware: bandwidth up is good, latency/overhead
down is good.  TPU-leg values captured from a stale snapshot
(``tpu_stale``) are annotated ``*`` and never flagged — a stale copy of
an old number is not a fresh regression.

    python scripts/bench_history.py            # table + flags
    python scripts/bench_history.py --json     # machine-readable
    python scripts/bench_history.py --strict   # exit 1 on regressions

Round records are the driver's shape: ``{n, cmd, rc, tail, parsed}``
where ``parsed`` (and/or the last JSON line of ``tail``) carries the
bench.py output; newer rounds add ``shm_*``, latency percentiles, and
``tpu_*`` keys.  Unknown keys are ignored, so the table grows as the
bench does.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# metric -> (direction, label); direction "up" = bigger is better
METRICS = {
    "value": ("up", "shm put/get harmonic GB/s"),
    "shm_put_gbps": ("up", "shm put GB/s"),
    "shm_get_gbps": ("up", "shm get GB/s"),
    "vs_baseline": ("up", "vs single-stream TCP"),
    "p50_read_latency_us": ("down", "p50 64KiB read us"),
    "p99_read_latency_us": ("down", "p99 64KiB read us"),
    "alloc_ms": ("down", "alloc p50 ms"),
    # the HBM->pool push path (the alloc-first zero-copy tentpole): live
    # captures emit these unprefixed; stale-snapshot copies ride the
    # tpu_-prefixed rows below with the usual staleness annotation
    "hbm_put_gbps": ("up", "HBM->store GB/s (live)"),
    "hbm_get_gbps": ("up", "store->HBM GB/s (live)"),
    "prefill_store_overhead": ("down", "store prefill x (live)"),
    "tpu_hbm_put_gbps": ("up", "HBM->store GB/s"),
    "tpu_hbm_get_gbps": ("up", "store->HBM GB/s"),
    "tpu_prefill_store_overhead": ("down", "store-attached prefill x"),
    "tpu_serving_ttft_p50_ms": ("down", "serving TTFT p50 ms"),
    "tpu_serving_ttft_p99_ms": ("down", "serving TTFT p99 ms"),
    "tpu_spec_speedup": ("up", "speculation speedup"),
    "tpu_pallas_speedup_vs_xla": ("up", "pallas vs XLA"),
    "goodput_rps": ("up", "serve goodput req/s"),
    "slo_attainment": ("up", "serve SLO attainment"),
    # the step profiler's serving-leg attribution (engine/stepprof.py):
    # device-drain share of step wall time and retrace pressure — a
    # round that turns the step loop host-bound or shape-polymorphic
    # is flagged here, not argued about
    "host_stall_frac": ("down", "serving host-stall frac"),
    "retraces_per_100_steps": ("down", "retraces / 100 steps"),
    # dispatch economy (the single-sync speculation work): compiled
    # programs launched per decoded token, and accepted draft tokens
    # per fused spec dispatch — the two numbers that turn "spec is
    # 0.53x at 0.938 acceptance" into an attributable regression
    "dispatches_per_token": ("down", "dispatches / decoded token"),
    "spec_accept_per_dispatch": ("up", "spec accepted / dispatch"),
    # the disaggregation verdict (bench_serve.py `disagg` block): fleet
    # TTFT/TPOT p99 over the same-decode-budget monolith's at the top
    # offered rate — < 1.0 means prefill/decode separation is paying;
    # the PD acceptance is ttft_ratio <= 1.0 with tpot_burst_ratio
    # measurably below it under a prefill-heavy mix
    "ttft_ratio": ("down", "disagg/monolith TTFT p99"),
    "tpot_burst_ratio": ("down", "disagg/monolith TPOT p99"),
    # the health plane's verdict on the serving run (bench_serve.py
    # `health` block): watchdog firing transitions during the sweep —
    # a round that starts paging under the same load is a regression
    # even when the raw latency rows stay green
    "alerts_fired": ("down", "serve alerts fired"),
    # the admission plane's verdict (bench_serve.py `admission` block):
    # 1 = goodput at the highest offered rate held ≥50% of the curve's
    # peak (graceful degradation), 0 = collapse — a round that loses
    # the plateau regressed the control loop itself
    "goodput_plateau": ("up", "goodput plateau under overload"),
    # usage-attribution plane (PR 15): fleet-wide share of prompt tokens
    # served from the store per bench_serve's /debug/usage join — the
    # cache paying for itself, trended
    "usage_reuse_ratio": ("up", "store-served prompt-token share"),
    # the multi-node cluster leg (bench.py --endpoints N): aggregate
    # fleet bandwidth through the consistent-hash router
    "cluster_put_gbps": ("up", "cluster put GB/s (aggregate)"),
    "cluster_get_gbps": ("up", "cluster get GB/s (aggregate)"),
    # the reshape plane (same leg): descriptor-batched membership
    # migration throughput, with the per-key fallback's number kept as
    # the comparison row — a round where the two converge means the
    # batched path silently degraded to per-key copies
    "migrate_gbps": ("up", "reshape migrate GB/s (batched)"),
    "migrate_gbps_per_key": ("up", "reshape migrate GB/s (per-key)"),
    # the session plane (bench_serve.py --conversation `sessions`
    # block): fraction of computed prompt tokens that were re-prefill
    # waste — context a prior turn already paid for — and the
    # session-affinity hit rate among re-visits.  A round where waste
    # climbs or stickiness drops broke the cross-turn KV-persistence
    # contract, not just a latency number
    "reprefill_waste_frac": ("down", "session re-prefill waste frac"),
    "affinity_hit_rate": ("up", "session affinity hit rate"),
    # the resumption plane (bench_serve.py `resumption` block): streams
    # that crossed at least one mid-stream splice during the sweep, and
    # the worst client-visible stall the splices cost — both down-good:
    # a healthy fleet resumes nothing, and when chaos rounds DO splice,
    # the stall ceiling is the client-experience number to hold
    "stream_resumes": ("down", "streams resumed mid-sweep"),
    "max_stall_ms": ("down", "worst client stall ms"),
    # the stage ledger's TTFT decomposition (bench_serve.py `critpath`
    # block, infinistore_tpu/critpath.py): per-stage p99 at sweep end —
    # a round where one stage's p99 climbs is a NAMED regression
    # (scripts/trace_diff.py diffs two captures the same way); absent
    # keys (no /debug/critpath on older rounds) skip silently
    "stage_p99_admission_wait_ms": ("down", "p99 admission_wait ms"),
    "stage_p99_queue_wait_ms": ("down", "p99 queue_wait ms"),
    "stage_p99_prefill_compute_ms": ("down", "p99 prefill_compute ms"),
    "stage_p99_kv_flush_ms": ("down", "p99 kv_flush ms"),
    "stage_p99_store_transfer_ms": ("down", "p99 store_transfer ms"),
    "stage_p99_decode_queue_ms": ("down", "p99 decode_queue ms"),
    "stage_p99_first_token_ms": ("down", "p99 first_token ms"),
    "stage_p99_per_token_decode_ms": ("down", "p99 per_token_decode ms"),
    "stage_p99_unattributed_ms": ("down", "p99 unattributed ms"),
}


def _last_json_line(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


_PAIR = re.compile(r'"([a-z0-9_]+)":\s*(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|'
                   r'true|false)(?=[,}\s])')


def _salvage_pairs(text: str):
    """Flat key/number pairs regex-scanned out of a TRUNCATED JSON
    fragment — the driver caps ``tail``, and a round whose record lost
    its opening brace (r05) would otherwise vanish from the trend."""
    out = {}
    for k, v in _PAIR.findall(text):
        if v in ("true", "false"):
            out[k] = v == "true"
        else:
            out[k] = float(v)
    return out


def load_round(path: Path):
    """One round's flat metric dict (numbers only) + its round number
    and staleness marker."""
    rec = json.loads(path.read_text())
    m = re.search(r"r(\d+)", path.stem)
    n = rec.get("n", int(m.group(1)) if m else 0)
    flat = {}
    parsed = rec.get("parsed") or {}
    tail = _last_json_line(rec.get("tail", ""))
    if tail is None:  # truncated fragment: salvage what scans
        tail = _salvage_pairs(rec.get("tail", ""))
    for src in (parsed, tail):  # tail is richer; parsed wins nothing new
        for k, v in src.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            flat.setdefault(k, float(v))
    stale = bool(parsed.get("tpu_stale") or tail.get("tpu_stale")
                 or tail.get("stale"))
    return n, flat, stale


def load_baseline():
    """Published reference numbers from BASELINE.json, when any are
    numeric (the seed repo ships an empty ``published`` section)."""
    path = REPO / "BASELINE.json"
    if not path.exists():
        return {}
    try:
        pub = json.loads(path.read_text()).get("published") or {}
    except ValueError:
        return {}
    return {k: float(v) for k, v in pub.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)}


def collect(repo: Path = REPO):
    rounds = []
    for path in sorted(repo.glob("BENCH_r*.json")):
        try:
            rounds.append(load_round(path))
        except (ValueError, OSError) as e:
            print(f"# skipping {path.name}: {e}", file=sys.stderr)
    rounds.sort(key=lambda r: r[0])
    return rounds


def regressions(rounds, tolerance: float):
    """Latest round vs the best prior round, per tracked metric.
    Returns ``{metric: {latest, best_prior, best_round, ratio}}`` for
    metrics that regressed past the tolerance.  Stale-TPU rounds are
    excluded on BOTH sides for tpu_* metrics."""
    if len(rounds) < 2:
        return {}
    latest_n, latest, latest_stale = rounds[-1]
    out = {}
    for key, (direction, _label) in METRICS.items():
        if key not in latest:
            continue
        if key.startswith("tpu_") and latest_stale:
            continue  # a stale snapshot is not a fresh measurement
        prior = [
            (n, flat[key]) for n, flat, stale in rounds[:-1]
            if key in flat and not (key.startswith("tpu_") and stale)
        ]
        if not prior:
            continue
        best_n, best = (max if direction == "up" else min)(
            prior, key=lambda p: p[1]
        )
        cur = latest[key]
        if best == 0:
            continue
        ratio = cur / best
        worse = ratio < (1 - tolerance) if direction == "up" \
            else ratio > (1 + tolerance)
        if worse:
            out[key] = {
                "latest": cur, "best_prior": best,
                "best_round": best_n, "latest_round": latest_n,
                "ratio": round(ratio, 3),
            }
    return out


def render(rounds, baseline, flagged):
    cols = [n for n, _f, _s in rounds]
    width = max((len(lbl) for _d, lbl in METRICS.values()), default=20) + 2
    head = f"{'metric':{width}s}" + "".join(f"{'r%02d' % n:>10s}" for n in cols)
    if baseline:
        head += f"{'baseline':>10s}"
    lines = [head, "-" * len(head)]
    for key, (_direction, label) in METRICS.items():
        if not any(key in flat for _n, flat, _s in rounds) \
                and key not in baseline:
            continue
        row = f"{label:{width}s}"
        for _n, flat, stale in rounds:
            v = flat.get(key)
            if v is None:
                row += f"{'-':>10s}"
            else:
                mark = "*" if key.startswith("tpu_") and stale else ""
                row += f"{_fmt(v) + mark:>10s}"
        if baseline:
            row += f"{_fmt(baseline[key]) if key in baseline else '-':>10s}"
        if key in flagged:
            f = flagged[key]
            row += (f"  REGRESSED vs r{f['best_round']:02d} "
                    f"({f['ratio']:.2f}x)")
        lines.append(row)
    if any(s for _n, _f, s in rounds):
        lines.append("* tpu leg served from a stale committed snapshot "
                     "(tunnel down at bench time) — not flagged")
    return "\n".join(lines)


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e6:
        return str(int(v))
    return f"{v:.3g}" if abs(v) >= 100 else f"{v:.3f}".rstrip("0").rstrip(".")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("bench_history.py")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="relative slack before a move counts as a "
                         "regression (default 5%%)")
    ap.add_argument("--json", action="store_true",
                    help="emit the joined rounds + flags as JSON")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any metric regressed")
    args = ap.parse_args(argv)
    rounds = collect()
    if not rounds:
        print("no BENCH_r*.json records found", file=sys.stderr)
        return 0
    baseline = load_baseline()
    flagged = regressions(rounds, args.tolerance)
    if args.json:
        print(json.dumps({
            "rounds": [
                {"round": n, "stale_tpu": s, "metrics": f}
                for n, f, s in rounds
            ],
            "baseline": baseline,
            "regressions": flagged,
        }, indent=2))
    else:
        print(render(rounds, baseline, flagged))
        if flagged:
            print(f"\n{len(flagged)} metric(s) regressed vs the best "
                  "prior round (see rows above)")
        else:
            print("\nno regressions vs best prior round "
                  f"(tolerance {args.tolerance:.0%})")
    return 1 if (args.strict and flagged) else 0


if __name__ == "__main__":
    sys.exit(main())
