#!/usr/bin/env python3
"""Metrics ↔ docs drift lint.

Every ``istpu_*`` metric family registered anywhere in the
``infinistore_tpu`` package must appear in ``docs/observability.md``, and
every ``istpu_*`` family the docs mention must actually be registered —
an inventory that silently rots is worse than none, because operators
build alerts from it.  Fails the build (exit 1) on drift in either
direction.

Static scan on purpose: registrations are string literals passed to
``.counter(`` / ``.gauge(`` / ``.histogram(``, so no servers (or shm
pools) need to be built to enumerate them.  Docs-side tokens support
``{a,b}`` brace expansion (``istpu_serve_{queue_wait,prefill}_p{50,99}_ms``)
and the ``_bucket`` / ``_sum`` / ``_count`` histogram suffixes used in
example queries.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "infinistore_tpu"
DOCS = REPO / "docs" / "observability.md"

_REG = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*[\"'](istpu_[a-z0-9_]+)[\"']"
)
# a docs token: istpu_ then runs of name chars and/or {a,b} expansion
# groups (label braces like {op="..."} contain '=' / '"' and do not match
# the group alternative, so they terminate the token — as they should)
_DOC_TOKEN = re.compile(r"istpu_(?:[a-z0-9_]+|\{[a-z0-9_,]+\})+")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def registered_families() -> set:
    names = set()
    for path in PKG.rglob("*.py"):
        names.update(_REG.findall(path.read_text()))
    return names


def _expand(token: str) -> set:
    m = re.search(r"\{([a-z0-9_,]+)\}", token)
    if m is None:
        return {token}
    out = set()
    for alt in m.group(1).split(","):
        out |= _expand(token[: m.start()] + alt + token[m.end():])
    return out


def documented_families(text: str, registered: set) -> set:
    names = set()
    for token in _DOC_TOKEN.findall(text):
        # a TRAILING brace group is a Prometheus label annotation
        # (`istpu_spec_kind{kind}`), not an expansion — labels always
        # follow the complete family name.  Inner groups
        # (`istpu_serve_{queue_wait,prefill}_p50_ms`) are expansions.
        token = re.sub(r"\{[a-z0-9_,]+\}$", "", token)
        if token.endswith("_"):
            continue  # wildcard prose like `istpu_cache_*`
        for name in _expand(token):
            # example PromQL uses derived series names; fold them back
            # onto their family when (and only when) the family exists
            for sfx in _HIST_SUFFIXES:
                if name.endswith(sfx) and name[: -len(sfx)] in registered:
                    name = name[: -len(sfx)]
                    break
            names.add(name)
    return names


def main() -> int:
    registered = registered_families()
    documented = documented_families(DOCS.read_text(), registered)
    undocumented = sorted(registered - documented)
    unregistered = sorted(documented - registered)
    if undocumented:
        print("metric families registered in code but MISSING from "
              f"{DOCS.relative_to(REPO)}:")
        for n in undocumented:
            print(f"  - {n}")
    if unregistered:
        print(f"metric families documented in {DOCS.relative_to(REPO)} "
              "but registered NOWHERE in the package:")
        for n in unregistered:
            print(f"  - {n}")
    if undocumented or unregistered:
        return 1
    print(f"metrics/docs lint OK: {len(registered)} families in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
