#!/usr/bin/env python3
"""Watchdog-rule ↔ runbook drift lint.

Every registered ``WatchdogRule`` name (the default serve + store rule
sets in ``infinistore_tpu/health.py``) must have a matching row in
``docs/runbook.md``'s rule tables, and every rule the runbook names must
actually be registered — the same both-directions contract the metrics
lint enforces for ``docs/observability.md``.  A runbook that silently
rots is worse than none, because it is the 3am map.

Imports the rule constructors (cheap — health.py pulls no jax) instead
of regex-scanning the source: rule names are built by factory calls
(``spike_rule("disk_errors", ...)``), which a static scan would have to
re-implement.  Fails the build (exit 1) on drift in either direction.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
RUNBOOK = REPO / "docs" / "runbook.md"

# a rule row: a table line whose first cell is a backticked rule name
_ROW = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|", re.MULTILINE)


def registered_rules() -> set:
    sys.path.insert(0, str(REPO))
    from infinistore_tpu.health import (
        default_serve_rules,
        default_store_rules,
    )

    return {r.name for r in default_serve_rules() + default_store_rules()}


def documented_rules(text: str) -> set:
    return set(_ROW.findall(text))


def main() -> int:
    registered = registered_rules()
    documented = documented_rules(RUNBOOK.read_text())
    undocumented = sorted(registered - documented)
    unregistered = sorted(documented - registered)
    if undocumented:
        print("watchdog rules registered in code but MISSING from "
              f"{RUNBOOK.relative_to(REPO)}:")
        for n in undocumented:
            print(f"  - {n}")
    if unregistered:
        print(f"rules documented in {RUNBOOK.relative_to(REPO)} but "
              "registered NOWHERE in the default rule sets:")
        for n in unregistered:
            print(f"  - {n}")
    if undocumented or unregistered:
        return 1
    print(f"runbook lint OK: {len(registered)} rules in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
