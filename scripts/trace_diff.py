#!/usr/bin/env python3
"""Automated critical-path regression naming: diff the stage
decompositions of two captures and NAME the stage that regressed.

"TTFT went from 80 ms to 130 ms" starts an argument; "store_transfer
went from 6 ms to 54 ms and owns 96% of the regression" ends one.  This
script takes two stage-decomposition captures — ``bench_serve.py
--json-out`` records (their ``critpath`` block / ``stage_p99_*_ms``
mirrors) or raw ``GET /debug/critpath`` payloads from two live windows —
and, per quantile, attributes the TTFT delta to the canonical stages
(infinistore_tpu/critpath.py), naming the dominant regressed stage with
its effect size:

    python scripts/trace_diff.py baseline.json candidate.json
    python scripts/trace_diff.py --quantile p50 before.json after.json
    python scripts/trace_diff.py --json a.json b.json   # machine-readable

Exit code: 0 when no stage regressed past ``--threshold-ms`` (default
5 ms), 2 when one did — usable as a perf gate.  The pure half
(:func:`diff_stages`) is imported by the chaos test that asserts a
FaultInjector-induced store delay is named ``store_transfer`` here, not
eyeballed from a timeline.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, Optional

# keep the canonical stage order without importing the package (the
# script must run from a bare checkout); cross-checked by the tier-1
# test against infinistore_tpu.critpath.STAGES
STAGES = (
    "admission_wait",
    "queue_wait",
    "prefill_compute",
    "kv_flush",
    "store_transfer",
    "decode_queue",
    "first_token",
    "per_token_decode",
    "unattributed",
)


def load_stages(obj: Dict[str, Any],
                quantile: str = "p99") -> Dict[str, float]:
    """Per-stage milliseconds out of any capture shape we emit:

    * a live ``/debug/critpath`` payload (``overall.stage_<q>_ms``);
    * a ``bench_serve --json-out`` record (its ``critpath`` block, or
      the flat ``stage_<q>_<stage>_ms`` mirrors);
    * an already-flat ``{stage: ms}`` dict (tests).
    """
    key = f"stage_{quantile}_ms"
    for block in (obj, obj.get("critpath") or {}):
        overall = block.get("overall") or block
        if isinstance(overall.get(key), dict):
            return {s: float(overall[key].get(s) or 0.0) for s in STAGES}
    flat = {s: obj.get(f"stage_{quantile}_{s}_ms") for s in STAGES}
    if any(v is not None for v in flat.values()):
        return {s: float(v or 0.0) for s, v in flat.items()}
    if all(isinstance(obj.get(s), (int, float)) for s in STAGES
           if s in obj) and any(s in obj for s in STAGES):
        return {s: float(obj.get(s) or 0.0) for s in STAGES}
    raise ValueError(
        f"no stage_{quantile} decomposition found (expected a "
        "/debug/critpath payload, a bench_serve --json-out record with "
        "a critpath block, or a flat stage dict)")


def diff_stages(base: Dict[str, float], cand: Dict[str, float],
                threshold_ms: float = 5.0) -> Dict[str, Any]:
    """Attribute the TTFT movement between two per-stage decompositions
    (pure; milliseconds in, a named verdict out).

    The regressed stage is the one with the largest positive delta; its
    effect size is reported absolutely (``delta_ms``), relatively
    (``ratio`` — candidate over baseline), and as its share of the
    total positive movement (``share_of_regression``).  ``regressed``
    is True only when that delta clears ``threshold_ms``, so noise-level
    jitter never names a culprit."""
    deltas = {s: round((cand.get(s) or 0.0) - (base.get(s) or 0.0), 3)
              for s in STAGES}
    total_up = sum(d for d in deltas.values() if d > 0)
    worst = max(STAGES, key=lambda s: deltas[s])
    worst_delta = deltas[worst]
    base_v = base.get(worst) or 0.0
    out = {
        "ttft_delta_ms": round(sum(deltas.values()), 3),
        "deltas_ms": deltas,
        "regressed": bool(worst_delta >= threshold_ms),
        "stage": worst if worst_delta > 0 else None,
        "delta_ms": worst_delta,
        "ratio": round((cand.get(worst) or 0.0) / base_v, 3)
        if base_v > 0 else None,
        "share_of_regression": round(worst_delta / total_up, 4)
        if total_up > 0 else 0.0,
    }
    return out


def _load(path: str) -> Dict[str, Any]:
    return json.loads(Path(path).read_text())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "trace_diff.py",
        description="name the regressed stage between two stage-"
                    "decomposition captures")
    ap.add_argument("baseline", help="baseline capture (bench_serve "
                                     "--json-out or /debug/critpath JSON)")
    ap.add_argument("candidate", help="candidate capture, same shapes")
    ap.add_argument("--quantile", default="p99", choices=("p50", "p99"),
                    help="which per-stage quantile to diff (default p99)")
    ap.add_argument("--threshold-ms", type=float, default=5.0,
                    help="minimum stage delta before a regression is "
                         "named (default 5 ms)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as JSON")
    args = ap.parse_args(argv)
    try:
        base = load_stages(_load(args.baseline), args.quantile)
        cand = load_stages(_load(args.candidate), args.quantile)
    except (OSError, ValueError) as e:
        print(f"trace_diff: {e}", file=sys.stderr)
        return 1
    verdict = diff_stages(base, cand, threshold_ms=args.threshold_ms)
    if args.json:
        print(json.dumps(verdict, indent=2))
    else:
        print(f"{'stage':22s}{'base ms':>10s}{'cand ms':>10s}"
              f"{'delta ms':>10s}")
        print("-" * 52)
        for s in STAGES:
            print(f"{s:22s}{base[s]:>10.3f}{cand[s]:>10.3f}"
                  f"{verdict['deltas_ms'][s]:>+10.3f}")
        print("-" * 52)
        print(f"{'TTFT-path total':22s}{sum(base.values()):>10.3f}"
              f"{sum(cand.values()):>10.3f}"
              f"{verdict['ttft_delta_ms']:>+10.3f}")
        if verdict["regressed"]:
            ratio = (f", {verdict['ratio']:.2f}x"
                     if verdict["ratio"] is not None else "")
            print(f"\nREGRESSED stage: {verdict['stage']} "
                  f"(+{verdict['delta_ms']:.1f} ms{ratio}; "
                  f"{verdict['share_of_regression']:.0%} of the total "
                  f"positive movement)")
        else:
            print(f"\nno stage regressed past "
                  f"{args.threshold_ms:.1f} ms at {args.quantile}")
    return 2 if verdict["regressed"] else 0


if __name__ == "__main__":
    sys.exit(main())
