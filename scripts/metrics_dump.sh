#!/usr/bin/env bash
# Quick-eyeball dump of both /metrics endpoints of a running stack.
#
#   scripts/metrics_dump.sh [serve_host:port] [store_manage_host:port]
#
# Defaults match the CLIs' defaults: serve.py on :8000, the store manage
# plane on :18080.  Either endpoint being down prints a warning instead
# of failing the other.

set -u
SERVE="${1:-127.0.0.1:8000}"
STORE="${2:-127.0.0.1:18080}"

fetch() {
    local label="$1" url="$2"
    echo "===== $label ($url) ====="
    if ! curl -fsS --max-time 5 "$url"; then
        echo "  [unreachable: $url]" >&2
    fi
    echo
}

fetch "serving /metrics" "http://$SERVE/metrics"
fetch "store /metrics" "http://$STORE/metrics"
fetch "store /healthz" "http://$STORE/healthz"
