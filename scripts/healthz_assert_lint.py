#!/usr/bin/env python3
"""House-rule lint: no exact-body ``/healthz`` asserts in tests.

The ``/healthz`` payload GROWS over time — PR 10 added the ``alerts``
block and broke a test that compared the whole body, PR 12 grows it
again with the ``admission`` block.  The standing rule (ROADMAP.md house
rules) is **field-level asserts only**: ``json.loads(data)["status"] ==
"ok"`` is fine, ``json.loads(data) == {"status": "ok"}`` is a time bomb.

Heuristic scan, tuned against the real suite: flag any equality/
inequality comparison against a dict literal within a few lines of a
``/healthz`` mention.  Synthetic *payload construction* (``"/healthz":
{"status": ...}`` fixtures) does not match — only comparisons do.
Exit 1 on any hit, printing file:line for each.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
TESTS = REPO / "tests"

# how many lines after a /healthz mention a whole-body compare is
# considered "about" that payload
WINDOW = 6

_HEALTHZ = re.compile(r"/healthz|healthz\s*\(")
# an equality compare against a dict literal: `== {` / `!= {` (fixture
# construction `"/healthz": {...}` and dict.get defaults don't match)
_BODY_EQ = re.compile(r"[=!]=\s*\{")


def scan_file(path: Path):
    lines = path.read_text().splitlines()
    hits = []
    mentions = [i for i, ln in enumerate(lines) if _HEALTHZ.search(ln)]
    for i in mentions:
        for j in range(i, min(len(lines), i + WINDOW + 1)):
            if _BODY_EQ.search(lines[j]):
                hits.append((j + 1, lines[j].strip()))
    return sorted(set(hits))


def main() -> int:
    bad = []
    for path in sorted(TESTS.glob("test_*.py")):
        for lineno, text in scan_file(path):
            bad.append(f"{path.relative_to(REPO)}:{lineno}: {text}")
    if bad:
        print("exact-body /healthz asserts found (house rule: the "
              "payload grows — assert FIELDS, never the whole body):")
        for b in bad:
            print(f"  {b}")
        return 1
    print("healthz assert lint OK: no exact-body /healthz compares in "
          f"{len(list(TESTS.glob('test_*.py')))} test files")
    return 0


if __name__ == "__main__":
    sys.exit(main())
