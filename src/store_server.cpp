// Data-plane server: single-threaded epoll event loop.
//
// Native counterpart of infinistore_tpu/pyserver.py and of the reference's
// libuv server (reference: src/infinistore.cpp:887-1029).  Same per-
// connection state machine (READ_HEADER -> READ_BODY -> optional payload
// streaming straight into pool memory, mirroring the reference's
// READ_VALUE_THROUGH_TCP state), same wire protocol as protocol.py, so
// Python and C++ clients are interchangeable.
//
// Concurrency model: one epoll thread owns all sockets; the Store is guarded
// by a mutex so the Python manage plane (purge/evict/stats via the C ABI,
// see istpu_c.cpp) can call in from other threads -- the reference instead
// queues manage ops onto the loop; a mutex is simpler and the ops are rare.
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>

#include "protocol.h"
#include "store.h"

namespace istpu {

namespace {
constexpr size_t kMaxBody = 1ULL << 30;

enum class ConnState { kHeader, kBody, kStreamPayload };

struct Conn {
  int fd;
  ConnState state = ConnState::kHeader;
  std::string in;          // accumulating header+body bytes
  size_t need = sizeof(Header);
  Header hdr{};
  std::string out;         // pending response bytes
  size_t out_off = 0;
  // zero-copy tail: segments sent straight from pool memory after `out`
  // (GET_INLINE_BATCH streams pool pages without building a copy; the
  // 5 s read lease keeps the entries alive while queued)
  std::vector<std::pair<const uint8_t*, uint64_t>> out_segs;
  size_t seg_idx = 0;
  uint64_t seg_off = 0;
  // payload streaming (PUT_INLINE_BATCH)
  std::vector<std::string> stream_keys;
  std::vector<Desc> stream_descs;
  size_t stream_idx = 0;
  uint64_t stream_off = 0;
  uint64_t discard_bytes = 0;  // drain-and-drop after a failed batch alloc
  int32_t discard_status = 0;
  // keys allocated but not yet committed by this connection
  std::vector<std::string> pending_keys;
};
}  // namespace

class StoreServer {
 public:
  StoreServer(const StoreConfig& cfg, int port) : store_(cfg), port_(port) {}

  ~StoreServer() { stop(); }

  bool start() {
    listen_fd_ = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        listen(listen_fd_, 128) != 0) {
      close(listen_fd_);
      listen_fd_ = -1;
      return false;
    }
    ep_fd_ = epoll_create1(0);
    wake_fd_ = eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = listen_fd_;
    epoll_ctl(ep_fd_, EPOLL_CTL_ADD, listen_fd_, &ev);
    ev.data.fd = wake_fd_;
    epoll_ctl(ep_fd_, EPOLL_CTL_ADD, wake_fd_, &ev);
    running_ = true;
    thread_ = std::thread([this] { loop(); });
    return true;
  }

  void stop() {
    if (!running_.exchange(false)) return;
    uint64_t one = 1;
    [[maybe_unused]] ssize_t r = write(wake_fd_, &one, sizeof(one));
    if (thread_.joinable()) thread_.join();
    for (auto& [fd, c] : conns_) close(fd);
    conns_.clear();
    if (listen_fd_ >= 0) close(listen_fd_);
    if (ep_fd_ >= 0) close(ep_fd_);
    if (wake_fd_ >= 0) close(wake_fd_);
    listen_fd_ = ep_fd_ = wake_fd_ = -1;
  }

  Store* store() { return &store_; }
  std::mutex* store_mutex() { return &mu_; }

 private:
  void loop() {
    epoll_event evs[64];
    while (running_) {
      int n = epoll_wait(ep_fd_, evs, 64, 500);
      for (int i = 0; i < n; i++) {
        int fd = evs[i].data.fd;
        if (fd == wake_fd_) {
          uint64_t v;
          [[maybe_unused]] ssize_t r = read(wake_fd_, &v, sizeof(v));
          continue;
        }
        if (fd == listen_fd_) {
          accept_conns();
          continue;
        }
        auto it = conns_.find(fd);
        if (it == conns_.end()) continue;
        Conn* c = it->second.get();
        bool alive = true;
        if (evs[i].events & (EPOLLHUP | EPOLLERR)) alive = false;
        if (alive && (evs[i].events & EPOLLIN)) alive = on_readable(c);
        if (alive && (evs[i].events & EPOLLOUT)) alive = flush(c);
        if (!alive) drop(fd);
      }
    }
  }

  void accept_conns() {
    while (true) {
      int fd = accept4(listen_fd_, nullptr, nullptr, SOCK_NONBLOCK);
      if (fd < 0) break;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto c = std::make_unique<Conn>();
      c->fd = fd;
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.fd = fd;
      epoll_ctl(ep_fd_, EPOLL_CTL_ADD, fd, &ev);
      conns_.emplace(fd, std::move(c));
    }
  }

  void drop(int fd) {
    auto it = conns_.find(fd);
    if (it == conns_.end()) return;
    if (!it->second->pending_keys.empty()) {
      // client went away mid-write: reclaim uncommitted regions
      std::lock_guard<std::mutex> g(mu_);
      store_.abort_put(it->second->pending_keys);
    }
    epoll_ctl(ep_fd_, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
    conns_.erase(it);
  }

  // returns false if the connection died
  bool on_readable(Conn* c) {
    char buf[1 << 16];
    while (true) {
      if (c->state == ConnState::kStreamPayload) {
        if (!stream_payload(c)) return false;
        if (c->state == ConnState::kStreamPayload) return true;  // EAGAIN
        continue;
      }
      size_t want = c->need - c->in.size();
      ssize_t r = recv(c->fd, buf, std::min(want, sizeof(buf)), 0);
      if (r == 0) return false;
      if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
      c->in.append(buf, r);
      if (c->in.size() < c->need) continue;
      if (c->state == ConnState::kHeader) {
        std::memcpy(&c->hdr, c->in.data(), sizeof(Header));
        if (c->hdr.magic != MAGIC || c->hdr.version != VERSION ||
            c->hdr.body_len > kMaxBody)
          return false;  // bad magic => reset (reference: connection teardown)
        c->in.clear();
        if (c->hdr.body_len == 0) {
          if (!dispatch(c, nullptr, 0)) return false;
        } else {
          c->state = ConnState::kBody;
          c->need = c->hdr.body_len;
        }
      } else {  // kBody complete
        std::string body = std::move(c->in);
        c->in.clear();
        c->state = ConnState::kHeader;
        c->need = sizeof(Header);
        if (!dispatch(c, reinterpret_cast<const uint8_t*>(body.data()),
                      body.size()))
          return false;
      }
      if (!c->out.empty() && !flush(c)) return false;
    }
  }

  // stream PUT_INLINE_BATCH payload straight into pool regions
  bool stream_payload(Conn* c) {
    if (c->discard_bytes) {  // failed alloc: drain payload to stay in sync
      char sink[1 << 16];
      while (c->discard_bytes) {
        ssize_t r = recv(c->fd, sink,
                         std::min<uint64_t>(c->discard_bytes, sizeof(sink)), 0);
        if (r == 0) return false;
        if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
        c->discard_bytes -= r;
      }
      respond(c, c->discard_status, "");
      c->state = ConnState::kHeader;
      c->need = sizeof(Header);
      return flush(c);
    }
    while (c->stream_idx < c->stream_descs.size()) {
      const Desc& d = c->stream_descs[c->stream_idx];
      uint8_t* dst;
      {
        std::lock_guard<std::mutex> g(mu_);
        dst = store_.view(d.pool_idx, d.offset);
      }
      while (c->stream_off < d.size) {
        ssize_t r = recv(c->fd, dst + c->stream_off, d.size - c->stream_off, 0);
        if (r == 0) goto dead;
        if (r < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
          goto dead;
        }
        c->stream_off += r;
      }
      c->stream_idx++;
      c->stream_off = 0;
    }
    {
      std::lock_guard<std::mutex> g(mu_);
      for (const auto& k : c->stream_keys) {
        Entry* e = store_.pending_entry(k);
        if (e) e->busy = false;
      }
      int32_t committed = 0;
      Status st = store_.commit_put(c->stream_keys, &committed);
      remove_pending(c, c->stream_keys);
      std::string body(reinterpret_cast<const char*>(&committed), 4);
      respond(c, st, body);
    }
    c->stream_keys.clear();
    c->stream_descs.clear();
    c->stream_idx = 0;
    c->state = ConnState::kHeader;
    c->need = sizeof(Header);
    return flush(c);
  dead : {
    std::lock_guard<std::mutex> g(mu_);
    for (const auto& k : c->stream_keys) {
      Entry* e = store_.pending_entry(k);
      if (e) e->busy = false;
    }
    store_.abort_put(c->stream_keys);
    remove_pending(c, c->stream_keys);
  }
    return false;
  }

  static void remove_pending(Conn* c, const std::vector<std::string>& keys) {
    for (const auto& k : keys) {
      for (auto it = c->pending_keys.begin(); it != c->pending_keys.end(); ++it) {
        if (*it == k) {
          c->pending_keys.erase(it);
          break;
        }
      }
    }
  }

  void respond(Conn* c, int32_t status, const std::string& body) {
    RespHeader rh{status, static_cast<uint32_t>(body.size())};
    c->out.append(reinterpret_cast<const char*>(&rh), sizeof(rh));
    c->out.append(body);
  }

  // returns false if the connection died; registers EPOLLOUT when blocked
  bool flush(Conn* c) {
    while (c->out_off < c->out.size()) {
      ssize_t r = send(c->fd, c->out.data() + c->out_off,
                       c->out.size() - c->out_off, MSG_NOSIGNAL);
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return want_out(c);
        return false;
      }
      c->out_off += r;
    }
    while (c->seg_idx < c->out_segs.size()) {
      auto [p, sz] = c->out_segs[c->seg_idx];
      ssize_t r = send(c->fd, p + c->seg_off, sz - c->seg_off, MSG_NOSIGNAL);
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return want_out(c);
        return false;
      }
      c->seg_off += r;
      if (c->seg_off == sz) {
        c->seg_idx++;
        c->seg_off = 0;
      }
    }
    c->out.clear();
    c->out_off = 0;
    c->out_segs.clear();
    c->seg_idx = 0;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = c->fd;
    epoll_ctl(ep_fd_, EPOLL_CTL_MOD, c->fd, &ev);
    return true;
  }

  bool want_out(Conn* c) {
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.fd = c->fd;
    epoll_ctl(ep_fd_, EPOLL_CTL_MOD, c->fd, &ev);
    return true;
  }

  bool dispatch(Conn* c, const uint8_t* body, size_t body_len) {
    Reader rd(body, body_len);
    std::lock_guard<std::mutex> g(mu_);
    switch (c->hdr.op) {
      case OP_HELLO:
      case OP_POOLS: {
        std::string out;
        Writer w(&out);
        const auto& pools = store_.mm().pools();
        w.put<uint32_t>(static_cast<uint32_t>(pools.size()));
        for (const auto& p : pools) {
          w.put<uint16_t>(static_cast<uint16_t>(p->name().size()));
          w.put_bytes(p->name().data(), p->name().size());
          w.put<uint64_t>(p->pool_size());
          w.put<uint64_t>(p->block_size());
        }
        respond(c, FINISH, out);
        return true;
      }
      case OP_PUT_INLINE: {
        uint16_t klen = rd.get<uint16_t>();
        std::string key;
        if (!rd.ok() || !rd.get_bytes(&key, klen)) return bad(c);
        uint64_t vlen = rd.get<uint64_t>();
        if (!rd.ok() || rd.remaining() != vlen) return bad(c);
        respond(c, store_.put_inline(key, body + (body_len - vlen), vlen), "");
        return true;
      }
      case OP_GET_INLINE: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys) || keys.empty()) return bad(c);
        const Entry* e = store_.get_inline(keys[0]);
        if (!e) {
          respond(c, KEY_NOT_FOUND, "");
          return true;
        }
        std::string out(reinterpret_cast<const char*>(store_.view(e->pool_idx, e->offset)),
                        e->size);
        respond(c, FINISH, out);
        return true;
      }
      case OP_ALLOC_PUT: {
        uint64_t block_size = rd.get<uint64_t>();
        std::vector<std::string> keys;
        if (!rd.ok() || !rd.get_keys(&keys)) return bad(c);
        std::vector<Desc> descs;
        Status st = store_.alloc_put(keys, block_size, &descs);
        if (st == FINISH)
          c->pending_keys.insert(c->pending_keys.end(), keys.begin(), keys.end());
        std::string out(reinterpret_cast<const char*>(descs.data()),
                        descs.size() * sizeof(Desc));
        respond(c, st, out);
        return true;
      }
      case OP_COMMIT_PUT: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys)) return bad(c);
        int32_t committed = 0;
        Status st = store_.commit_put(keys, &committed);
        remove_pending(c, keys);
        respond(c, st, std::string(reinterpret_cast<const char*>(&committed), 4));
        return true;
      }
      case OP_GET_DESC: {
        uint64_t block_size = rd.get<uint64_t>();
        std::vector<std::string> keys;
        if (!rd.ok() || !rd.get_keys(&keys)) return bad(c);
        std::vector<Desc> descs;
        Status st = store_.get_desc(keys, block_size, &descs);
        std::string out(reinterpret_cast<const char*>(descs.data()),
                        descs.size() * sizeof(Desc));
        respond(c, st, out);
        return true;
      }
      case OP_EXIST: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys) || keys.empty()) return bad(c);
        int32_t v = store_.exist(keys[0]) ? 0 : 1;
        respond(c, FINISH, std::string(reinterpret_cast<const char*>(&v), 4));
        return true;
      }
      case OP_MATCH_LAST_IDX: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys)) return bad(c);
        int32_t v = store_.match_last_index(keys);
        respond(c, FINISH, std::string(reinterpret_cast<const char*>(&v), 4));
        return true;
      }
      case OP_DELETE_KEYS: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys)) return bad(c);
        int32_t v = store_.delete_keys(keys);
        respond(c, FINISH, std::string(reinterpret_cast<const char*>(&v), 4));
        return true;
      }
      case OP_PURGE: {
        int32_t v = store_.purge();
        respond(c, FINISH, std::string(reinterpret_cast<const char*>(&v), 4));
        return true;
      }
      case OP_STATS: {
        respond(c, FINISH, store_.stats_json());
        return true;
      }
      case OP_EVICT: {
        float mn = rd.get<float>(), mx = rd.get<float>();
        if (!rd.ok()) return bad(c);
        store_.evict(mn, mx);
        respond(c, FINISH, "");
        return true;
      }
      case OP_PUT_INLINE_BATCH: {
        uint64_t block_size = rd.get<uint64_t>();
        std::vector<std::string> keys;
        if (!rd.ok() || !rd.get_keys(&keys)) return bad(c);
        std::vector<Desc> descs;
        Status st = store_.alloc_put(keys, block_size, &descs);
        if (st != FINISH) {
          // payload still arrives; drain it so the stream stays in sync
          // (pyserver.py does the same)
          c->discard_bytes = block_size * keys.size();
          c->discard_status = st;
          c->state = ConnState::kStreamPayload;
          return true;
        }
        for (const auto& k : keys) {
          Entry* e = store_.pending_entry(k);
          if (e) e->busy = true;  // purge must not free mid-stream regions
        }
        c->pending_keys.insert(c->pending_keys.end(), keys.begin(), keys.end());
        c->stream_keys = std::move(keys);
        c->stream_descs = std::move(descs);
        c->stream_idx = 0;
        c->stream_off = 0;
        c->state = ConnState::kStreamPayload;
        return true;
      }
      case OP_GET_INLINE_BATCH: {
        uint64_t block_size = rd.get<uint64_t>();
        std::vector<std::string> keys;
        if (!rd.ok() || !rd.get_keys(&keys)) return bad(c);
        std::vector<Desc> descs;
        Status st = store_.get_desc(keys, block_size, &descs);
        if (st != FINISH) {
          respond(c, st, "");
          return true;
        }
        uint64_t total = 0;
        for (const auto& d : descs) total += d.size;
        // resp = sizes array in `out`, payloads streamed from pool memory
        std::string sizes;
        sizes.reserve(4 * descs.size());
        for (const auto& d : descs) {
          uint32_t sz = static_cast<uint32_t>(d.size);
          sizes.append(reinterpret_cast<const char*>(&sz), 4);
        }
        RespHeader rh{FINISH, static_cast<uint32_t>(sizes.size() + total)};
        c->out.append(reinterpret_cast<const char*>(&rh), sizeof(rh));
        c->out.append(sizes);
        for (const auto& d : descs) {
          c->out_segs.emplace_back(store_.view(d.pool_idx, d.offset), d.size);
        }
        return true;
      }
      default:
        return bad(c);
    }
  }

  bool bad(Conn* c) {
    respond(c, INVALID_REQ, "");
    return true;
  }

  Store store_;
  std::mutex mu_;
  int port_;
  int listen_fd_ = -1;
  int ep_fd_ = -1;
  int wake_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread thread_;
  std::unordered_map<int, std::unique_ptr<Conn>> conns_;
};

}  // namespace istpu

// factory used by the C ABI (istpu_c.cpp)
namespace istpu {
StoreServer* make_server(const StoreConfig& cfg, int port) {
  return new StoreServer(cfg, port);
}
bool server_start(StoreServer* s) { return s->start(); }
void server_stop(StoreServer* s) { s->stop(); }
void server_destroy(StoreServer* s) { delete s; }
Store* server_store(StoreServer* s) { return s->store(); }
std::mutex* server_mutex(StoreServer* s) { return s->store_mutex(); }
}  // namespace istpu
