// Data-plane server: single-threaded epoll event loop.
//
// Native counterpart of infinistore_tpu/pyserver.py and of the reference's
// libuv server (reference: src/infinistore.cpp:887-1029).  Same per-
// connection state machine (READ_HEADER -> READ_BODY -> optional payload
// streaming straight into pool memory, mirroring the reference's
// READ_VALUE_THROUGH_TCP state), same wire protocol as protocol.py, so
// Python and C++ clients are interchangeable.
//
// Concurrency model: one epoll thread owns all sockets; the Store is guarded
// by a mutex so the Python manage plane (purge/evict/stats via the C ABI,
// see istpu_c.cpp) can call in from other threads -- the reference instead
// queues manage ops onto the loop; a mutex is simpler and the ops are rare.
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <chrono>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>

#include "protocol.h"
#include "store.h"

namespace istpu {

namespace {
constexpr size_t kMaxBody = 1ULL << 30;

enum class ConnState { kHeader, kBody, kStreamPayload };

struct Conn {
  int fd;
  int ep_fd = -1;  // the shard's epoll fd this connection lives on
  ConnState state = ConnState::kHeader;
  std::string in;          // accumulating header+body bytes
  size_t need = sizeof(Header);
  Header hdr{};
  // Ordered output queue.  With pipelined clients several responses can be
  // queued before the first finishes flushing, and a response may mix
  // copied bytes (headers/sizes) with zero-copy pool segments
  // (GET_INLINE_BATCH payloads) -- the queue preserves wire order across
  // both kinds.  Segment items borrow pool pages, which stay pinned in the
  // Store until the queue drains.
  struct OutItem {
    std::string bytes;             // used when seg == nullptr
    const uint8_t* seg = nullptr;  // borrowed pool pointer otherwise
    uint64_t size = 0;             // seg length (bytes items use bytes.size())
  };
  std::deque<OutItem> outq;
  uint64_t out_off = 0;            // send offset into outq.front()
  std::vector<Desc> seg_descs;     // pinned regions backing queued segments
  // payload streaming (PUT_INLINE_BATCH)
  std::vector<std::string> stream_keys;
  std::vector<Desc> stream_descs;
  size_t stream_idx = 0;
  uint64_t stream_off = 0;
  uint64_t discard_bytes = 0;  // drain-and-drop after a failed batch alloc
  int32_t discard_status = 0;
  // keys allocated but not yet committed by this connection.  A SET:
  // commit removes its whole batch here, and the old vector scan made
  // that O(batch^2) string compares — the dominant per-key put overhead
  // at serving batch sizes (2048-key rounds)
  std::unordered_set<std::string> pending_keys;
};
}  // namespace

class StoreServer {
 public:
  StoreServer(const StoreConfig& cfg, int port) : store_(cfg), port_(port) {
    // Payload streaming (socket <-> pool memcpy) runs outside the store
    // mutex, so sharding connections across event loops scales the data
    // plane across cores -- the role the NIC's DMA engines play for the
    // reference's RDMA path.  Metadata ops stay serialized on the mutex.
    const char* env = getenv("ISTPU_SERVER_LOOPS");
    int n = env ? atoi(env) : 0;
    if (n <= 0) {
      unsigned hw = std::thread::hardware_concurrency();
      n = hw ? std::min(4u, hw) : 2;
    }
    for (int i = 0; i < n; i++) shards_.push_back(std::make_unique<Shard>());
  }

  ~StoreServer() { stop(); }

  bool start() {
    listen_fd_ = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        listen(listen_fd_, 128) != 0) {
      close(listen_fd_);
      listen_fd_ = -1;
      return false;
    }
    running_ = true;
    for (size_t s = 0; s < shards_.size(); s++) {
      Shard& sh = *shards_[s];
      sh.ep_fd = epoll_create1(0);
      sh.wake_fd = eventfd(0, EFD_NONBLOCK);
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.fd = sh.wake_fd;
      epoll_ctl(sh.ep_fd, EPOLL_CTL_ADD, sh.wake_fd, &ev);
      if (s == 0) {  // shard 0 also owns the listen socket
        ev.data.fd = listen_fd_;
        epoll_ctl(sh.ep_fd, EPOLL_CTL_ADD, listen_fd_, &ev);
      }
      sh.thread = std::thread([this, &sh] { loop(sh); });
    }
    return true;
  }

  void stop() {
    if (!running_.exchange(false)) return;
    for (auto& shp : shards_) {
      uint64_t one = 1;
      [[maybe_unused]] ssize_t r = write(shp->wake_fd, &one, sizeof(one));
    }
    for (auto& shp : shards_) {
      Shard& sh = *shp;
      if (sh.thread.joinable()) sh.thread.join();
      for (auto& [fd, c] : sh.conns) close(fd);
      sh.conns.clear();
      if (sh.ep_fd >= 0) close(sh.ep_fd);
      if (sh.wake_fd >= 0) close(sh.wake_fd);
      sh.ep_fd = sh.wake_fd = -1;
    }
    if (listen_fd_ >= 0) close(listen_fd_);
    listen_fd_ = -1;
  }

  Store* store() { return &store_; }
  std::mutex* store_mutex() { return &mu_; }
  std::string stats_json_full() {
    std::lock_guard<std::mutex> g(mu_);
    return stats_json_locked();
  }

 private:
  struct Shard {
    int ep_fd = -1;
    int wake_fd = -1;
    std::thread thread;
    std::mutex conns_mu;  // accept thread inserts, shard thread finds/erases
    std::unordered_map<int, std::unique_ptr<Conn>> conns;
  };

  void loop(Shard& sh) {
    epoll_event evs[64];
    while (running_) {
      int n = epoll_wait(sh.ep_fd, evs, 64, 500);
      for (int i = 0; i < n; i++) {
        int fd = evs[i].data.fd;
        if (fd == sh.wake_fd) {
          uint64_t v;
          [[maybe_unused]] ssize_t r = read(sh.wake_fd, &v, sizeof(v));
          continue;
        }
        if (fd == listen_fd_) {
          accept_conns();
          continue;
        }
        Conn* c;
        {
          std::lock_guard<std::mutex> g(sh.conns_mu);
          auto it = sh.conns.find(fd);
          if (it == sh.conns.end()) continue;
          c = it->second.get();
        }
        bool alive = true;
        // a malformed frame must cost the sender its connection, never the
        // process: any exception out of parsing/dispatch (e.g. bad_alloc on
        // an adversarial length) drops the connection
        try {
          if (evs[i].events & (EPOLLHUP | EPOLLERR)) alive = false;
          if (alive && (evs[i].events & EPOLLIN)) alive = on_readable(c);
          if (alive && (evs[i].events & EPOLLOUT)) alive = flush(c);
        } catch (const std::exception&) {
          alive = false;
        }
        if (!alive) drop(sh, fd);
      }
    }
  }

  void accept_conns() {
    while (true) {
      int fd = accept4(listen_fd_, nullptr, nullptr, SOCK_NONBLOCK);
      if (fd < 0) break;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      Shard& sh = *shards_[next_shard_++ % shards_.size()];
      auto c = std::make_unique<Conn>();
      c->fd = fd;
      c->ep_fd = sh.ep_fd;
      {
        std::lock_guard<std::mutex> g(sh.conns_mu);
        sh.conns.emplace(fd, std::move(c));
      }
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.fd = fd;
      epoll_ctl(sh.ep_fd, EPOLL_CTL_ADD, fd, &ev);
    }
  }

  void drop(Shard& sh, int fd) {
    std::unique_ptr<Conn> c;
    {
      std::lock_guard<std::mutex> g(sh.conns_mu);
      auto it = sh.conns.find(fd);
      if (it == sh.conns.end()) return;
      c = std::move(it->second);
      sh.conns.erase(it);
    }
    if (!c->pending_keys.empty() || !c->seg_descs.empty()) {
      std::lock_guard<std::mutex> g(mu_);
      // client went away mid-write: reclaim uncommitted regions
      if (!c->pending_keys.empty())
        store_.abort_put(std::vector<std::string>(c->pending_keys.begin(),
                                                  c->pending_keys.end()));
      // release pins on zero-copy segments it never finished receiving
      if (!c->seg_descs.empty()) store_.unpin(c->seg_descs);
    }
    epoll_ctl(sh.ep_fd, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
  }

  // returns false if the connection died
  bool on_readable(Conn* c) {
    char buf[1 << 16];
    while (true) {
      if (c->state == ConnState::kStreamPayload) {
        if (!stream_payload(c)) return false;
        if (c->state == ConnState::kStreamPayload) return true;  // EAGAIN
        continue;
      }
      size_t want = c->need - c->in.size();
      ssize_t r = recv(c->fd, buf, std::min(want, sizeof(buf)), 0);
      if (r == 0) return false;
      if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
      c->in.append(buf, r);
      if (c->in.size() < c->need) continue;
      if (c->state == ConnState::kHeader) {
        std::memcpy(&c->hdr, c->in.data(), sizeof(Header));
        if (c->hdr.magic != MAGIC || c->hdr.version != VERSION ||
            c->hdr.body_len > kMaxBody)
          return false;  // bad magic => reset (reference: connection teardown)
        c->in.clear();
        if (c->hdr.body_len == 0) {
          if (!dispatch(c, nullptr, 0)) return false;
        } else {
          c->state = ConnState::kBody;
          c->need = c->hdr.body_len;
        }
      } else {  // kBody complete
        std::string body = std::move(c->in);
        c->in.clear();
        c->state = ConnState::kHeader;
        c->need = sizeof(Header);
        if (!dispatch(c, reinterpret_cast<const uint8_t*>(body.data()),
                      body.size()))
          return false;
      }
      if (!c->outq.empty() && !flush(c)) return false;
    }
  }

  // env-gated data-plane timing (ISTPU_TIMING=1): cumulative seconds spent
  // in recv-into-pool vs everything else, printed per 256 MB streamed
  struct Timing {
    double recv_s = 0, total_bytes = 0;
    std::chrono::steady_clock::time_point win_start =
        std::chrono::steady_clock::now();
  };
  Timing timing_;
  bool timing_on_ = getenv("ISTPU_TIMING") != nullptr;

  // stream PUT_INLINE_BATCH payload straight into pool regions
  bool stream_payload(Conn* c) {
    if (c->discard_bytes) {  // failed alloc: drain payload to stay in sync
      char sink[1 << 16];
      while (c->discard_bytes) {
        ssize_t r = recv(c->fd, sink,
                         std::min<uint64_t>(c->discard_bytes, sizeof(sink)), 0);
        if (r == 0) return false;
        if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
        c->discard_bytes -= r;
      }
      respond(c, c->discard_status, "");
      c->state = ConnState::kHeader;
      c->need = sizeof(Header);
      return flush(c);
    }
    while (c->stream_idx < c->stream_descs.size()) {
      const Desc& d = c->stream_descs[c->stream_idx];
      uint8_t* dst;
      {
        std::lock_guard<std::mutex> g(mu_);
        dst = store_.view(d.pool_idx, d.offset);
      }
      while (c->stream_off < d.size) {
        auto t0 = timing_on_ ? std::chrono::steady_clock::now()
                             : std::chrono::steady_clock::time_point();
        ssize_t r = recv(c->fd, dst + c->stream_off, d.size - c->stream_off, 0);
        if (timing_on_ && r > 0) {
          timing_.recv_s += std::chrono::duration<double>(
                                std::chrono::steady_clock::now() - t0)
                                .count();
          timing_.total_bytes += r;
          if (timing_.total_bytes >= (256 << 20)) {
            double win = std::chrono::duration<double>(
                             std::chrono::steady_clock::now() -
                             timing_.win_start)
                             .count();
            fprintf(stderr,
                    "[istpu-timing] %.0f MB window: recv %.3fs (%.2f GB/s "
                    "inside recv), wall %.3fs (%.2f GB/s)\n",
                    timing_.total_bytes / 1e6, timing_.recv_s,
                    timing_.total_bytes / timing_.recv_s / 1e9, win,
                    timing_.total_bytes / win / 1e9);
            timing_ = Timing();
          }
        }
        if (r == 0) goto dead;
        if (r < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
          goto dead;
        }
        c->stream_off += r;
      }
      c->stream_idx++;
      c->stream_off = 0;
    }
    {
      std::lock_guard<std::mutex> g(mu_);
      for (const auto& k : c->stream_keys) {
        Entry* e = store_.pending_entry(k);
        if (e) e->busy = false;
      }
      int32_t committed = 0;
      Status st = store_.commit_put(c->stream_keys, &committed);
      remove_pending(c, c->stream_keys);
      std::string body(reinterpret_cast<const char*>(&committed), 4);
      respond(c, st, body);
    }
    c->stream_keys.clear();
    c->stream_descs.clear();
    c->stream_idx = 0;
    c->state = ConnState::kHeader;
    c->need = sizeof(Header);
    return flush(c);
  dead : {
    std::lock_guard<std::mutex> g(mu_);
    for (const auto& k : c->stream_keys) {
      Entry* e = store_.pending_entry(k);
      if (e) e->busy = false;
    }
    store_.abort_put(c->stream_keys);
    remove_pending(c, c->stream_keys);
  }
    return false;
  }

  static void remove_pending(Conn* c, const std::vector<std::string>& keys) {
    for (const auto& k : keys) c->pending_keys.erase(k);
  }

  void queue_bytes(Conn* c, std::string bytes) {
    // coalesce consecutive byte items (headers of back-to-back small
    // responses share one send)
    if (!c->outq.empty() && c->outq.back().seg == nullptr &&
        !(c->outq.size() == 1 && c->out_off > 0)) {
      c->outq.back().bytes.append(bytes);
      return;
    }
    Conn::OutItem item;
    item.bytes = std::move(bytes);
    c->outq.push_back(std::move(item));
  }

  void queue_seg(Conn* c, const uint8_t* p, uint64_t size) {
    Conn::OutItem item;
    item.seg = p;
    item.size = size;
    c->outq.push_back(std::move(item));
  }

  void respond(Conn* c, int32_t status, const std::string& body) {
    RespHeader rh{status, static_cast<uint32_t>(body.size())};
    std::string bytes(reinterpret_cast<const char*>(&rh), sizeof(rh));
    bytes.append(body);
    queue_bytes(c, std::move(bytes));
  }

  // returns false if the connection died; registers EPOLLOUT when blocked
  bool flush(Conn* c) {
    while (!c->outq.empty()) {
      Conn::OutItem& item = c->outq.front();
      const uint8_t* base = item.seg
                                ? item.seg
                                : reinterpret_cast<const uint8_t*>(item.bytes.data());
      uint64_t size = item.seg ? item.size : item.bytes.size();
      while (c->out_off < size) {
        ssize_t r = send(c->fd, base + c->out_off, size - c->out_off,
                         MSG_NOSIGNAL);
        if (r < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK) return want_out(c);
          return false;
        }
        c->out_off += r;
      }
      c->out_off = 0;
      c->outq.pop_front();
    }
    if (!c->seg_descs.empty()) {
      std::lock_guard<std::mutex> g(mu_);
      store_.unpin(c->seg_descs);
      c->seg_descs.clear();
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = c->fd;
    epoll_ctl(c->ep_fd, EPOLL_CTL_MOD, c->fd, &ev);
    return true;
  }

  bool want_out(Conn* c) {
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.fd = c->fd;
    epoll_ctl(c->ep_fd, EPOLL_CTL_MOD, c->fd, &ev);
    return true;
  }

  // per-op server-side latency accumulators (count, total_s, max_s):
  // the server half of observability next to the client's latency_stats
  // (reference analog: per-op timing visibility on the data plane)
  struct OpLatency { uint64_t count = 0; double total_s = 0, max_s = 0; };

  std::string stats_json_locked() {
    // store stats + the server-side per-op latency section (callers hold mu_)
    std::string js = store_.stats_json();
    js.pop_back();  // trailing '}'
    return js + ", \"op_latency\": " + op_latency_json() + "}";
  }

  std::string op_latency_json() {
    std::string out = "{";
    bool first = true;
    for (const auto& [op, s] : op_lat_) {
      char buf[160];
      snprintf(buf, sizeof(buf),
               "%s\"%s\": {\"count\": %llu, \"avg_ms\": %.3f, \"max_ms\": %.3f}",
               first ? "" : ", ", op_name(op),
               static_cast<unsigned long long>(s.count),
               s.count ? s.total_s / s.count * 1e3 : 0.0, s.max_s * 1e3);
      out += buf;
      first = false;
    }
    return out + "}";
  }

  bool dispatch(Conn* c, const uint8_t* body, size_t body_len) {
    Reader rd(body, body_len);
    std::lock_guard<std::mutex> g(mu_);
    // scope-exit timing so every early return of the switch is covered
    struct Timed {
      StoreServer* s;
      uint8_t op;
      std::chrono::steady_clock::time_point t0 =
          std::chrono::steady_clock::now();
      ~Timed() {
        double dt = std::chrono::duration<double>(
                        std::chrono::steady_clock::now() - t0)
                        .count();
        auto& rec = s->op_lat_[op];
        rec.count++;
        rec.total_s += dt;
        if (dt > rec.max_s) rec.max_s = dt;
      }
    } timed{this, c->hdr.op};
    switch (c->hdr.op) {
      case OP_HELLO:
      case OP_POOLS: {
        std::string out;
        Writer w(&out);
        const auto& pools = store_.mm().pools();
        w.put<uint32_t>(static_cast<uint32_t>(pools.size()));
        for (const auto& p : pools) {
          w.put<uint16_t>(static_cast<uint16_t>(p->name().size()));
          w.put_bytes(p->name().data(), p->name().size());
          w.put<uint64_t>(p->pool_size());
          w.put<uint64_t>(p->block_size());
        }
        respond(c, FINISH, out);
        return true;
      }
      case OP_PUT_INLINE: {
        uint16_t klen = rd.get<uint16_t>();
        std::string key;
        if (!rd.ok() || !rd.get_bytes(&key, klen)) return bad(c);
        uint64_t vlen = rd.get<uint64_t>();
        if (!rd.ok() || rd.remaining() != vlen) return bad(c);
        respond(c, store_.put_inline(key, body + (body_len - vlen), vlen), "");
        return true;
      }
      case OP_GET_INLINE: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys) || keys.empty()) return bad(c);
        const Entry* e = store_.get_inline(keys[0]);
        if (!e) {
          respond(c, KEY_NOT_FOUND, "");
          return true;
        }
        std::string out(reinterpret_cast<const char*>(store_.view(e->pool_idx, e->offset)),
                        e->size);
        respond(c, FINISH, out);
        return true;
      }
      case OP_ALLOC_PUT: {
        uint64_t block_size = rd.get<uint64_t>();
        std::vector<std::string> keys;
        if (!rd.ok() || !rd.get_keys(&keys)) return bad(c);
        std::vector<Desc> descs;
        Status st = store_.alloc_put(keys, block_size, &descs);
        if (st == FINISH)
          c->pending_keys.insert(keys.begin(), keys.end());
        std::string out(reinterpret_cast<const char*>(descs.data()),
                        descs.size() * sizeof(Desc));
        respond(c, st, out);
        return true;
      }
      case OP_COMMIT_PUT: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys)) return bad(c);
        int32_t committed = 0;
        Status st = store_.commit_put(keys, &committed);
        remove_pending(c, keys);
        respond(c, st, std::string(reinterpret_cast<const char*>(&committed), 4));
        return true;
      }
      case OP_GET_DESC: {
        uint64_t block_size = rd.get<uint64_t>();
        std::vector<std::string> keys;
        if (!rd.ok() || !rd.get_keys(&keys)) return bad(c);
        std::vector<Desc> descs;
        Status st = store_.get_desc(keys, block_size, &descs);
        std::string out(reinterpret_cast<const char*>(descs.data()),
                        descs.size() * sizeof(Desc));
        respond(c, st, out);
        return true;
      }
      case OP_EXIST: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys) || keys.empty()) return bad(c);
        int32_t v = store_.exist(keys[0]) ? 0 : 1;
        respond(c, FINISH, std::string(reinterpret_cast<const char*>(&v), 4));
        return true;
      }
      case OP_MATCH_LAST_IDX: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys)) return bad(c);
        int32_t v = store_.match_last_index(keys);
        respond(c, FINISH, std::string(reinterpret_cast<const char*>(&v), 4));
        return true;
      }
      case OP_DELETE_KEYS: {
        std::vector<std::string> keys;
        if (!rd.get_keys(&keys)) return bad(c);
        int32_t v = store_.delete_keys(keys);
        respond(c, FINISH, std::string(reinterpret_cast<const char*>(&v), 4));
        return true;
      }
      case OP_PURGE: {
        int32_t v = store_.purge();
        respond(c, FINISH, std::string(reinterpret_cast<const char*>(&v), 4));
        return true;
      }
      case OP_STATS: {
        respond(c, FINISH, stats_json_locked());
        return true;
      }
      case OP_EVICT: {
        float mn = rd.get<float>(), mx = rd.get<float>();
        if (!rd.ok()) return bad(c);
        store_.evict(mn, mx);
        respond(c, FINISH, "");
        return true;
      }
      case OP_PUT_INLINE_BATCH: {
        uint64_t block_size = rd.get<uint64_t>();
        std::vector<std::string> keys;
        if (!rd.ok() || !rd.get_keys(&keys)) return bad(c);
        std::vector<Desc> descs;
        Status st = store_.alloc_put(keys, block_size, &descs);
        if (st != FINISH) {
          // payload still arrives; drain it so the stream stays in sync
          // (pyserver.py does the same)
          c->discard_bytes = block_size * keys.size();
          c->discard_status = st;
          c->state = ConnState::kStreamPayload;
          return true;
        }
        for (const auto& k : keys) {
          Entry* e = store_.pending_entry(k);
          if (e) e->busy = true;  // purge must not free mid-stream regions
        }
        c->pending_keys.insert(keys.begin(), keys.end());
        c->stream_keys = std::move(keys);
        c->stream_descs = std::move(descs);
        c->stream_idx = 0;
        c->stream_off = 0;
        c->state = ConnState::kStreamPayload;
        return true;
      }
      case OP_GET_INLINE_BATCH: {
        uint64_t block_size = rd.get<uint64_t>();
        std::vector<std::string> keys;
        if (!rd.ok() || !rd.get_keys(&keys)) return bad(c);
        std::vector<Desc> descs;
        Status st = store_.get_desc(keys, block_size, &descs);
        if (st != FINISH) {
          respond(c, st, "");
          return true;
        }
        uint64_t total = 0;
        for (const auto& d : descs) total += d.size;
        // resp = sizes array in `out`, payloads streamed from pool memory
        std::string sizes;
        sizes.reserve(4 * descs.size());
        for (const auto& d : descs) {
          uint32_t sz = static_cast<uint32_t>(d.size);
          sizes.append(reinterpret_cast<const char*>(&sz), 4);
        }
        RespHeader rh{FINISH, static_cast<uint32_t>(sizes.size() + total)};
        std::string head(reinterpret_cast<const char*>(&rh), sizeof(rh));
        head.append(sizes);
        queue_bytes(c, std::move(head));
        store_.pin(descs);  // pages stay alive until flush() finishes sending
        for (const auto& d : descs) {
          queue_seg(c, store_.view(d.pool_idx, d.offset), d.size);
        }
        c->seg_descs.insert(c->seg_descs.end(), descs.begin(), descs.end());
        return true;
      }
      default:
        return bad(c);
    }
  }

  bool bad(Conn* c) {
    respond(c, INVALID_REQ, "");
    return true;
  }

  Store store_;
  std::mutex mu_;
  std::unordered_map<uint8_t, OpLatency> op_lat_;  // guarded by mu_
  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::atomic<size_t> next_shard_{0};
  std::vector<std::unique_ptr<Shard>> shards_;
};

}  // namespace istpu

// factory used by the C ABI (istpu_c.cpp)
namespace istpu {
StoreServer* make_server(const StoreConfig& cfg, int port) {
  return new StoreServer(cfg, port);
}
bool server_start(StoreServer* s) { return s->start(); }
void server_stop(StoreServer* s) { s->stop(); }
void server_destroy(StoreServer* s) { delete s; }
Store* server_store(StoreServer* s) { return s->store(); }
std::mutex* server_mutex(StoreServer* s) { return s->store_mutex(); }
std::string server_stats_json(StoreServer* s) { return s->stats_json_full(); }
}  // namespace istpu
