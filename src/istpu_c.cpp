// C ABI for the native server - driven from Python via ctypes
// (infinistore_tpu/_native.py), replacing the reference's pybind11 module
// (reference: src/pybind.cpp) since pybind11 isn't in the image.
#include <cstring>
#include <mutex>
#include <string>

#include "store.h"

namespace istpu {
class StoreServer;
StoreServer* make_server(const StoreConfig& cfg, int port);
bool server_start(StoreServer* s);
void server_stop(StoreServer* s);
void server_destroy(StoreServer* s);
Store* server_store(StoreServer* s);
std::mutex* server_mutex(StoreServer* s);
std::string server_stats_json(StoreServer* s);
}  // namespace istpu

using istpu::Store;
using istpu::StoreConfig;
using istpu::StoreServer;

extern "C" {

// Bump whenever any extern-C signature changes: _native.py refuses a
// stale libistpu.so (existence-only checks would silently call an old
// signature and drop the new arguments).
int istpu_abi_version(void) { return 3; }

void* istpu_server_create(const char* shm_prefix, uint64_t prealloc_bytes,
                          uint64_t block_bytes, int auto_increase, int port,
                          const char* disk_tier_path,
                          uint64_t disk_tier_bytes, const char* allocator) {
  StoreConfig cfg;
  cfg.shm_prefix = shm_prefix ? shm_prefix : "";
  cfg.prealloc_bytes = prealloc_bytes;
  cfg.block_bytes = block_bytes;
  cfg.auto_increase = auto_increase != 0;
  cfg.disk_tier_path = disk_tier_path ? disk_tier_path : "";
  cfg.disk_tier_bytes = disk_tier_bytes;
  cfg.allocator = allocator ? allocator : "bitmap";
  try {
    return istpu::make_server(cfg, port);
  } catch (...) {
    return nullptr;
  }
}

int istpu_server_start(void* h) {
  return istpu::server_start(static_cast<StoreServer*>(h)) ? 0 : -1;
}

void istpu_server_stop(void* h) { istpu::server_stop(static_cast<StoreServer*>(h)); }

void istpu_server_destroy(void* h) {
  auto* s = static_cast<StoreServer*>(h);
  istpu::server_stop(s);
  istpu::server_destroy(s);
}

uint64_t istpu_server_kvmap_len(void* h) {
  auto* s = static_cast<StoreServer*>(h);
  std::lock_guard<std::mutex> g(*istpu::server_mutex(s));
  return istpu::server_store(s)->kvmap_len();
}

int istpu_server_purge(void* h) {
  auto* s = static_cast<StoreServer*>(h);
  std::lock_guard<std::mutex> g(*istpu::server_mutex(s));
  return istpu::server_store(s)->purge();
}

long long istpu_server_evict(void* h, double mn, double mx) {
  auto* s = static_cast<StoreServer*>(h);
  std::lock_guard<std::mutex> g(*istpu::server_mutex(s));
  return istpu::server_store(s)->evict(mn, mx);
}

double istpu_server_usage(void* h) {
  auto* s = static_cast<StoreServer*>(h);
  std::lock_guard<std::mutex> g(*istpu::server_mutex(s));
  return istpu::server_store(s)->usage();
}

int istpu_server_stats_json(void* h, char* buf, int cap) {
  auto* s = static_cast<StoreServer*>(h);
  // includes the server-layer op_latency section (locks internally)
  std::string j = istpu::server_stats_json(s);
  int n = std::min<int>(cap - 1, j.size());
  std::memcpy(buf, j.data(), n);
  buf[n] = 0;
  return n;
}

}  // extern "C"
