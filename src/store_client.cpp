// Native client engine - counterpart of the reference's libinfinistore.cpp
// Connection (reference: src/libinfinistore.cpp: TCP socket + RDMA QP, CQ
// thread, batched WR chains).  Here the zero-copy path maps the server's
// /dev/shm pools and memcpys blocks directly (the RDMA-WRITE/READ analog on
// a shared TPU-VM host); remote clients use the inline batch ops over TCP.
//
// Concurrency model (the analog of the reference's async WR chains +
// cq_handler thread, src/libinfinistore.cpp:103,596):
//  * every channel (socket) is PIPELINED: requests are sent under a short
//    send lock and matched FIFO by a dedicated reader thread, so many
//    Python threads can have ops in flight on one connection at once;
//  * TCP connections open `nstreams` channels and batched inline ops
//    STRIPE their blocks across them, with per-chunk sender threads, so
//    payload bandwidth scales across cores/flows;
//  * payloads move with vectored IO (sendmsg/recvmsg) - one syscall per
//    chunk instead of one per block.
// Python drives this via ctypes, which releases the GIL around every call.
#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "protocol.h"

#ifndef MADV_POPULATE_WRITE
#define MADV_POPULATE_WRITE 23
#endif

namespace istpu {

struct MappedPool {
  std::string name;
  uint8_t* base = nullptr;
  uint64_t size = 0;
};

namespace {

constexpr int kMaxIov = 64;  // < IOV_MAX; chunks larger than this loop

// Copy-thread count for striping large shm batches across cores (a single
// core's memcpy tops out well below DRAM bandwidth; the reference's RDMA
// NIC had the same role of outrunning one CPU stream).  0/1 disables.
size_t copy_threads() {
  static const size_t n = [] {
    if (const char* e = getenv("ISTPU_COPY_THREADS")) {
      long v = atol(e);
      return static_cast<size_t>(v < 1 ? 1 : (v > 16 ? 16 : v));
    }
    unsigned hw = std::thread::hardware_concurrency();
    return static_cast<size_t>(hw >= 8 ? 4 : (hw >= 4 ? 2 : 1));
  }();
  return n;
}

// Run copy_one(i) for i in [0, n) striped over copy_threads() threads when
// the batch is big enough to amortize thread spawn (~20 us each).
template <typename F>
void striped_copy(size_t n, uint64_t total_bytes, F&& copy_one) {
  size_t nt = std::min(copy_threads(), n);
  if (nt <= 1 || total_bytes < (8u << 20)) {
    for (size_t i = 0; i < n; i++) copy_one(i);
    return;
  }
  size_t per = (n + nt - 1) / nt;
  std::vector<std::thread> ts;
  ts.reserve(nt - 1);
  for (size_t t = 1; t < nt; t++) {
    size_t lo = t * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    ts.emplace_back([&copy_one, lo, hi] {
      for (size_t i = lo; i < hi; i++) copy_one(i);
    });
  }
  for (size_t i = 0; i < std::min(per, n); i++) copy_one(i);
  for (auto& t : ts) t.join();
}

// One in-flight request, resolved by its channel's reader thread.
struct Slot {
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  int32_t status = SYSTEM_ERROR;
  std::string resp;  // simple responses land here...
  // ...scatter responses (GET_INLINE_BATCH) land straight in caller memory:
  uint8_t* scatter_base = nullptr;
  const uint64_t* scatter_offs = nullptr;
  size_t scatter_n = 0;

  void wait() {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [this] { return done; });
  }
  void finish(int32_t st) {
    {
      std::lock_guard<std::mutex> lk(mu);
      status = st;
      done = true;
    }
    cv.notify_one();
  }
};

class Chan {
 public:
  ~Chan() { shutdown_close(); }

  int connect_to(const char* host, int port) {
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -2;
    if (connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      return -3;
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return 0;
  }

  // synchronous exchange, only valid before start_reader() (HELLO bootstrap)
  int32_t exchange(uint8_t op, const std::string& body, std::string* resp) {
    Header hdr{MAGIC, VERSION, op, 0, static_cast<uint32_t>(body.size()), 0};
    if (!send_all(&hdr, sizeof(hdr))) return SYSTEM_ERROR;
    if (!body.empty() && !send_all(body.data(), body.size()))
      return SYSTEM_ERROR;
    RespHeader rh;
    if (!recv_all(&rh, sizeof(rh))) return SYSTEM_ERROR;
    resp->resize(rh.body_len);
    if (rh.body_len && !recv_all(resp->data(), rh.body_len)) return SYSTEM_ERROR;
    return rh.status;
  }

  void start_reader() {
    reader_ = std::thread([this] { reader_loop(); });
  }

  // Send one framed request (header+body+optional payload iovecs) and
  // enqueue `slot` for the reader.  Returns false if the channel is dead.
  bool submit(Slot* slot, uint8_t op, const std::string& body,
              const struct iovec* payload, int payload_cnt) {
    std::lock_guard<std::mutex> g(send_mu_);
    if (dead_) return false;
    {
      std::lock_guard<std::mutex> q(q_mu_);
      q_.push_back(slot);
    }
    Header hdr{MAGIC, VERSION, op, 0, static_cast<uint32_t>(body.size()), 0};
    struct iovec head[2];
    head[0] = {const_cast<Header*>(&hdr), sizeof(hdr)};
    head[1] = {const_cast<char*>(body.data()), body.size()};
    bool ok = send_iov(head, body.empty() ? 1 : 2);
    if (ok && payload_cnt) ok = send_iov(payload, payload_cnt);
    if (!ok) {
      fail_all();
      return false;
    }
    return true;
  }

  void shutdown_close() {
    if (fd_ >= 0) shutdown(fd_, SHUT_RDWR);
    if (reader_.joinable()) reader_.join();
    if (fd_ >= 0) {
      close(fd_);
      fd_ = -1;
    }
  }

  bool alive() const { return !dead_; }

 private:
  void reader_loop() {
    while (true) {
      RespHeader rh;
      if (!recv_all(&rh, sizeof(rh))) break;
      Slot* slot;
      {
        std::lock_guard<std::mutex> q(q_mu_);
        if (q_.empty()) break;  // protocol desync: kill the channel
        slot = q_.front();
        q_.pop_front();
      }
      if (slot->scatter_base && rh.status == FINISH) {
        if (!consume_scatter(slot, rh.body_len)) {
          slot->finish(SYSTEM_ERROR);
          break;
        }
        slot->finish(rh.status);
        continue;
      }
      slot->resp.resize(rh.body_len);
      if (rh.body_len && !recv_all(slot->resp.data(), rh.body_len)) {
        slot->finish(SYSTEM_ERROR);
        break;
      }
      slot->finish(rh.status);
    }
    fail_all();
  }

  // GET_INLINE_BATCH response: n x size:u32, then payloads -> scatter
  // straight into the caller's buffer with readv
  bool consume_scatter(Slot* slot, uint32_t body_len) {
    size_t n = slot->scatter_n;
    std::vector<uint32_t> sizes(n);
    if (!recv_all(sizes.data(), 4 * n)) return false;
    uint64_t total = 0;
    for (auto s : sizes) total += s;
    if (4 * n + total != body_len) return false;  // framing mismatch
    std::vector<struct iovec> iov(n);
    for (size_t i = 0; i < n; i++) {
      iov[i].iov_base = slot->scatter_base + slot->scatter_offs[i];
      iov[i].iov_len = sizes[i];
    }
    return recv_iov(iov.data(), static_cast<int>(n));
  }

  void fail_all() {
    dead_ = true;
    std::deque<Slot*> q;
    {
      std::lock_guard<std::mutex> g(q_mu_);
      q.swap(q_);
    }
    for (Slot* s : q) s->finish(SYSTEM_ERROR);
  }

  bool send_all(const void* p, size_t n) {
    const char* b = static_cast<const char*>(p);
    while (n) {
      ssize_t r = send(fd_, b, n, MSG_NOSIGNAL);
      if (r <= 0) return false;
      b += r;
      n -= r;
    }
    return true;
  }

  bool send_iov(const struct iovec* iov, int cnt) {
    // loop over <= kMaxIov windows, advancing across partial sends
    std::vector<struct iovec> cur(iov, iov + cnt);
    size_t idx = 0;
    while (idx < cur.size()) {
      int take = static_cast<int>(std::min<size_t>(cur.size() - idx, kMaxIov));
      msghdr msg{};
      msg.msg_iov = &cur[idx];
      msg.msg_iovlen = take;
      ssize_t r = sendmsg(fd_, &msg, MSG_NOSIGNAL);
      if (r <= 0) return false;
      size_t left = static_cast<size_t>(r);
      while (left && idx < cur.size()) {
        if (left >= cur[idx].iov_len) {
          left -= cur[idx].iov_len;
          idx++;
        } else {
          cur[idx].iov_base = static_cast<char*>(cur[idx].iov_base) + left;
          cur[idx].iov_len -= left;
          left = 0;
        }
      }
    }
    return true;
  }

  bool recv_all(void* p, size_t n) const {
    char* b = static_cast<char*>(p);
    while (n) {
      ssize_t r = recv(fd_, b, n, 0);
      if (r <= 0) return false;
      b += r;
      n -= r;
    }
    return true;
  }

  bool recv_iov(struct iovec* iov, int cnt) const {
    std::vector<struct iovec> cur(iov, iov + cnt);
    size_t idx = 0;
    // skip zero-length entries up front
    while (idx < cur.size() && cur[idx].iov_len == 0) idx++;
    while (idx < cur.size()) {
      int take = static_cast<int>(std::min<size_t>(cur.size() - idx, kMaxIov));
      msghdr msg{};
      msg.msg_iov = &cur[idx];
      msg.msg_iovlen = take;
      ssize_t r = recvmsg(fd_, &msg, 0);
      if (r <= 0) return false;
      size_t left = static_cast<size_t>(r);
      while (left && idx < cur.size()) {
        if (left >= cur[idx].iov_len) {
          left -= cur[idx].iov_len;
          idx++;
        } else {
          cur[idx].iov_base = static_cast<char*>(cur[idx].iov_base) + left;
          cur[idx].iov_len -= left;
          left = 0;
        }
      }
      while (idx < cur.size() && cur[idx].iov_len == 0) idx++;
    }
    return true;
  }

  int fd_ = -1;
  std::mutex send_mu_;
  std::mutex q_mu_;
  std::deque<Slot*> q_;
  std::thread reader_;
  std::atomic<bool> dead_{false};
};

}  // namespace

class Client {
 public:
  ~Client() { close_conn(); }

  // returns 0 on success, negative errno-style on failure
  int connect_to(const char* host, int port, bool use_shm, int nstreams) {
    if (nstreams < 1) nstreams = 1;
    if (nstreams > 64) nstreams = 64;
    if (use_shm) nstreams = 1;  // payload never rides the socket in shm mode
    for (int i = 0; i < nstreams; i++) {
      auto ch = std::make_unique<Chan>();
      int rc = ch->connect_to(host, port);
      if (rc != 0) return rc;
      std::string body;
      Writer w(&body);
      w.put<uint32_t>(static_cast<uint32_t>(getpid()));
      w.put<uint32_t>(0);
      std::string resp;
      if (ch->exchange(OP_HELLO, body, &resp) != FINISH) return -4;
      if (i == 0 && !parse_pool_table(resp)) return -5;
      ch->start_reader();
      chans_.push_back(std::move(ch));
    }
    shm_ = use_shm;
    if (shm_ && !map_pools()) return -6;
    return 0;
  }

  void close_conn() {
    for (auto& ch : chans_) ch->shutdown_close();
    chans_.clear();
    for (auto& p : pools_) {
      if (p.base) munmap(p.base, p.size);
      p.base = nullptr;
    }
    pools_.clear();
  }

  // ---- batched zero-copy ops (reference: rdma_write_cache / rdma_read_cache) ----

  int32_t write_cache(const char* const* keys, const uint64_t* offsets, size_t n,
                      uint64_t block_size, const uint8_t* base) {
    if (shm_) {
      std::string body = pack_block_req(keys, n, block_size);
      std::string resp;
      int32_t st = request(OP_ALLOC_PUT, body, &resp);
      for (int retry = 0; retry < 20 && st == RETRY; retry++) {
        usleep(50000);
        st = request(OP_ALLOC_PUT, body, &resp);
      }
      if (st != FINISH) return st;
      size_t nd = resp.size() / sizeof(Desc);
      if (nd != n) return INTERNAL_ERROR;
      const Desc* descs = reinterpret_cast<const Desc*>(resp.data());
      // merge adjacent descriptors (contiguous pool bytes AND contiguous
      // client bytes) into runs: one large memcpy per run instead of one
      // per block — the payoff of the server's contiguous-run allocation
      struct Run { uint8_t* dst; const uint8_t* src; uint64_t len; };
      std::vector<Run> runs;
      runs.reserve(n);
      for (size_t i = 0; i < n; i++) {
        uint8_t* dst = pool_ptr(descs[i].pool_idx, descs[i].offset);
        if (!dst) return INTERNAL_ERROR;
        const uint8_t* src = base + offsets[i];
        if (!runs.empty() && runs.back().dst + runs.back().len == dst &&
            runs.back().src + runs.back().len == src) {
          runs.back().len += block_size;
        } else {
          runs.push_back({dst, src, block_size});
        }
      }
      striped_copy(runs.size(), n * block_size, [&](size_t i) {
        std::memcpy(runs[i].dst, runs[i].src, runs[i].len);
      });
      std::string commit;
      Writer w(&commit);
      put_keys(&w, keys, n);
      std::string resp2;
      return request(OP_COMMIT_PUT, commit, &resp2);
    }
    // inline path: stripe blocks across channels, one sender thread per
    // chunk so the payload copies into the kernel overlap
    size_t nch = std::min(chans_.size(), n);
    std::vector<int32_t> st(nch, FINISH);
    auto send_chunk = [&](size_t ci) {
      size_t per = (n + nch - 1) / nch;
      size_t lo = ci * per, hi = std::min(n, lo + per);
      if (lo >= hi) return;
      std::string body;
      Writer w(&body);
      w.put<uint64_t>(block_size);
      w.put<uint32_t>(static_cast<uint32_t>(hi - lo));
      for (size_t i = lo; i < hi; i++) {
        size_t klen = strlen(keys[i]);
        w.put<uint16_t>(static_cast<uint16_t>(klen));
        w.put_bytes(keys[i], klen);
      }
      std::vector<struct iovec> iov(hi - lo);
      for (size_t i = lo; i < hi; i++) {
        iov[i - lo].iov_base = const_cast<uint8_t*>(base + offsets[i]);
        iov[i - lo].iov_len = block_size;
      }
      Slot slot;
      if (!chans_[ci]->submit(&slot, OP_PUT_INLINE_BATCH, body, iov.data(),
                              static_cast<int>(iov.size()))) {
        st[ci] = SYSTEM_ERROR;
        return;
      }
      slot.wait();
      st[ci] = slot.status;
    };
    std::vector<std::thread> threads;
    for (size_t ci = 1; ci < nch; ci++)
      threads.emplace_back(send_chunk, ci);
    send_chunk(0);
    for (auto& t : threads) t.join();
    for (int32_t s : st)
      if (s != FINISH) return s;
    return FINISH;
  }

  int32_t read_cache(const char* const* keys, const uint64_t* offsets, size_t n,
                     uint64_t block_size, uint8_t* base) {
    if (shm_) {
      std::string body = pack_block_req(keys, n, block_size);
      std::string resp;
      int32_t st = request(OP_GET_DESC, body, &resp);
      if (st != FINISH) return st;
      size_t nd = resp.size() / sizeof(Desc);
      if (nd != n) return INTERNAL_ERROR;
      const Desc* descs = reinterpret_cast<const Desc*>(resp.data());
      struct Run { uint8_t* dst; const uint8_t* src; uint64_t len; };
      std::vector<Run> runs;
      runs.reserve(n);
      uint64_t total = 0;
      for (size_t i = 0; i < n; i++) {
        const uint8_t* src = pool_ptr(descs[i].pool_idx, descs[i].offset);
        if (!src) return INTERNAL_ERROR;
        uint8_t* dst = base + offsets[i];
        total += descs[i].size;
        if (!runs.empty() && runs.back().src + runs.back().len == src &&
            runs.back().dst + runs.back().len == dst) {
          runs.back().len += descs[i].size;
        } else {
          runs.push_back({dst, src, descs[i].size});
        }
      }
      striped_copy(runs.size(), total, [&](size_t i) {
        std::memcpy(runs[i].dst, runs[i].src, runs[i].len);
      });
      return FINISH;
    }
    // inline path: stripe the batch; each chunk's payload scatter-reads on
    // its channel's reader thread, so chunks drain in parallel
    size_t nch = std::min(chans_.size(), n);
    size_t per = (n + nch - 1) / nch;
    std::vector<std::unique_ptr<Slot>> slots;
    std::vector<int32_t> st(nch, FINISH);
    bool submitted_any = false;
    for (size_t ci = 0; ci < nch; ci++) {
      size_t lo = ci * per, hi = std::min(n, lo + per);
      if (lo >= hi) {
        slots.push_back(nullptr);
        continue;
      }
      std::string body;
      Writer w(&body);
      w.put<uint64_t>(block_size);
      w.put<uint32_t>(static_cast<uint32_t>(hi - lo));
      for (size_t i = lo; i < hi; i++) {
        size_t klen = strlen(keys[i]);
        w.put<uint16_t>(static_cast<uint16_t>(klen));
        w.put_bytes(keys[i], klen);
      }
      auto slot = std::make_unique<Slot>();
      slot->scatter_base = base;
      slot->scatter_offs = offsets + lo;
      slot->scatter_n = hi - lo;
      if (!chans_[ci]->submit(slot.get(), OP_GET_INLINE_BATCH, body, nullptr, 0))
        st[ci] = SYSTEM_ERROR;
      else
        submitted_any = true;
      slots.push_back(std::move(slot));
    }
    for (size_t ci = 0; ci < nch; ci++) {
      if (slots[ci] && st[ci] == FINISH) {
        slots[ci]->wait();
        st[ci] = slots[ci]->status;
      }
    }
    (void)submitted_any;
    for (int32_t s : st)
      if (s != FINISH) return s;
    return FINISH;
  }

  // ---- single-key inline ----

  int32_t put_inline(const char* key, const uint8_t* data, uint64_t size) {
    std::string body;
    Writer w(&body);
    size_t klen = strlen(key);
    w.put<uint16_t>(static_cast<uint16_t>(klen));
    w.put_bytes(key, klen);
    w.put<uint64_t>(size);
    w.put_bytes(data, size);
    std::string resp;
    return request(OP_PUT_INLINE, body, &resp);
  }

  // out must hold cap bytes; *out_size gets stored size (fails if > cap)
  int32_t get_inline(const char* key, uint8_t* out, uint64_t cap,
                     uint64_t* out_size) {
    std::string body;
    Writer w(&body);
    put_keys(&w, &key, 1);
    std::string resp;
    int32_t st = request(OP_GET_INLINE, body, &resp);
    if (st != FINISH) return st;
    *out_size = resp.size();
    if (resp.size() > cap) return INVALID_REQ;  // caller buffer too small
    std::memcpy(out, resp.data(), resp.size());
    return FINISH;
  }

  // ---- metadata ----

  int32_t simple_i32(uint8_t op, const char* const* keys, size_t n, int32_t* out) {
    std::string body;
    Writer w(&body);
    put_keys(&w, keys, n);
    std::string resp;
    int32_t st = request(op, body, &resp);
    if (st == FINISH && resp.size() >= 4) std::memcpy(out, resp.data(), 4);
    return st;
  }

  int32_t purge(int32_t* out) {
    std::string resp;
    int32_t st = request(OP_PURGE, "", &resp);
    if (st == FINISH && resp.size() >= 4) std::memcpy(out, resp.data(), 4);
    return st;
  }

  int32_t evict(float mn, float mx) {
    std::string body;
    Writer w(&body);
    w.put<float>(mn);
    w.put<float>(mx);
    std::string resp;
    return request(OP_EVICT, body, &resp);
  }

  int32_t stats_json(char* buf, int cap) {
    std::string resp;
    int32_t st = request(OP_STATS, "", &resp);
    if (st != FINISH) return st;
    int n = std::min<int>(cap - 1, resp.size());
    std::memcpy(buf, resp.data(), n);
    buf[n] = 0;
    return FINISH;
  }

 private:
  static std::string pack_block_req(const char* const* keys, size_t n,
                                    uint64_t block_size) {
    std::string body;
    Writer w(&body);
    w.put<uint64_t>(block_size);
    put_keys(&w, keys, n);
    return body;
  }

  static void put_keys(Writer* w, const char* const* keys, size_t n) {
    w->put<uint32_t>(static_cast<uint32_t>(n));
    for (size_t i = 0; i < n; i++) {
      size_t klen = strlen(keys[i]);
      w->put<uint16_t>(static_cast<uint16_t>(klen));
      w->put_bytes(keys[i], klen);
    }
  }

  // pipelined request/response on channel 0 (metadata + shm control plane)
  int32_t request(uint8_t op, const std::string& body, std::string* resp) {
    if (chans_.empty()) return SYSTEM_ERROR;
    Slot slot;
    if (!chans_[0]->submit(&slot, op, body, nullptr, 0)) return SYSTEM_ERROR;
    slot.wait();
    *resp = std::move(slot.resp);
    return slot.status;
  }

  bool parse_pool_table(const std::string& resp) {
    Reader rd(reinterpret_cast<const uint8_t*>(resp.data()), resp.size());
    uint32_t n = rd.get<uint32_t>();
    if (!rd.ok()) return false;
    std::vector<MappedPool> table;
    for (uint32_t i = 0; i < n; i++) {
      uint16_t nlen = rd.get<uint16_t>();
      MappedPool p;
      if (!rd.ok() || !rd.get_bytes(&p.name, nlen)) return false;
      p.size = rd.get<uint64_t>();
      rd.get<uint64_t>();  // block_size (informational)
      if (!rd.ok()) return false;
      table.push_back(std::move(p));
    }
    // preserve existing mappings by name
    for (auto& np : table) {
      for (auto& op : pools_) {
        if (op.base && op.name == np.name) {
          np.base = op.base;
          op.base = nullptr;
        }
      }
    }
    for (auto& op : pools_) {
      if (op.base) munmap(op.base, op.size);
    }
    pools_ = std::move(table);
    return true;
  }

  bool map_pools() {
    for (auto& p : pools_) {
      if (p.base) continue;
      std::string path = "/dev/shm/" + p.name;
      int fd = open(path.c_str(), O_RDWR);
      if (fd < 0) return false;
      void* m = mmap(nullptr, p.size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
      close(fd);
      if (m == MAP_FAILED) return false;
      // server already populated the pages; this maps them into our page
      // table so the data path takes no minor faults
      madvise(m, p.size, MADV_POPULATE_WRITE);
      p.base = static_cast<uint8_t*>(m);
    }
    return true;
  }

  uint8_t* pool_ptr(uint32_t idx, uint64_t off) {
    if (idx >= pools_.size() || !pools_[idx].base) {
      // pool table grew (auto-extend): refresh + remap.  Guarded so two
      // caller threads don't remap concurrently.
      std::lock_guard<std::mutex> g(pool_mu_);
      if (idx >= pools_.size() || !pools_[idx].base) {
        std::string resp;
        if (request(OP_POOLS, "", &resp) != FINISH || !parse_pool_table(resp) ||
            !map_pools() || idx >= pools_.size())
          return nullptr;
      }
    }
    return pools_[idx].base + off;
  }

  bool shm_ = false;
  std::vector<std::unique_ptr<Chan>> chans_;
  std::vector<MappedPool> pools_;
  std::mutex pool_mu_;
};

Client* make_client() { return new Client(); }

}  // namespace istpu

// ---- C ABI for ctypes (infinistore_tpu/_native.py) ----

using istpu::Client;

extern "C" {

void* istpu_client_create() { return new Client(); }

int istpu_client_connect(void* h, const char* host, int port, int use_shm,
                         int nstreams) {
  return static_cast<Client*>(h)->connect_to(host, port, use_shm != 0,
                                             nstreams);
}

void istpu_client_close(void* h) { static_cast<Client*>(h)->close_conn(); }
void istpu_client_destroy(void* h) { delete static_cast<Client*>(h); }

int istpu_client_write_cache(void* h, const char* const* keys,
                             const uint64_t* offsets, int n,
                             uint64_t block_size, const void* base) {
  return static_cast<Client*>(h)->write_cache(
      keys, offsets, n, block_size, static_cast<const uint8_t*>(base));
}

int istpu_client_read_cache(void* h, const char* const* keys,
                            const uint64_t* offsets, int n, uint64_t block_size,
                            void* base) {
  return static_cast<Client*>(h)->read_cache(keys, offsets, n, block_size,
                                             static_cast<uint8_t*>(base));
}

int istpu_client_put_inline(void* h, const char* key, const void* data,
                            uint64_t size) {
  return static_cast<Client*>(h)->put_inline(
      key, static_cast<const uint8_t*>(data), size);
}

int istpu_client_get_inline(void* h, const char* key, void* out, uint64_t cap,
                            uint64_t* out_size) {
  return static_cast<Client*>(h)->get_inline(key, static_cast<uint8_t*>(out),
                                             cap, out_size);
}

int istpu_client_exist(void* h, const char* key, int* out) {
  int32_t v = 0;
  int st = static_cast<Client*>(h)->simple_i32(istpu::OP_EXIST, &key, 1, &v);
  *out = v;
  return st;
}

int istpu_client_match_last_index(void* h, const char* const* keys, int n,
                                  int* out) {
  int32_t v = -1;
  int st = static_cast<Client*>(h)->simple_i32(istpu::OP_MATCH_LAST_IDX, keys,
                                               n, &v);
  *out = v;
  return st;
}

int istpu_client_delete_keys(void* h, const char* const* keys, int n, int* out) {
  int32_t v = 0;
  int st = static_cast<Client*>(h)->simple_i32(istpu::OP_DELETE_KEYS, keys, n, &v);
  *out = v;
  return st;
}

int istpu_client_purge(void* h, int* out) {
  int32_t v = 0;
  int st = static_cast<Client*>(h)->purge(&v);
  *out = v;
  return st;
}

int istpu_client_evict(void* h, float mn, float mx) {
  return static_cast<Client*>(h)->evict(mn, mx);
}

int istpu_client_stats_json(void* h, char* buf, int cap) {
  return static_cast<Client*>(h)->stats_json(buf, cap);
}

}  // extern "C"
