// Native client engine - counterpart of the reference's libinfinistore.cpp
// Connection (reference: src/libinfinistore.cpp: TCP socket + RDMA QP,
// batched WR chains).  Here the zero-copy path maps the server's /dev/shm
// pools and memcpys blocks directly (the RDMA-WRITE/READ analog on a shared
// TPU-VM host); remote clients use the inline batch ops over TCP.
//
// All calls are blocking on one socket; Python drives them via ctypes, which
// releases the GIL around foreign calls - the GIL-free IO the reference gets
// from its CQ-polling thread.
#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

#include "protocol.h"

#ifndef MADV_POPULATE_WRITE
#define MADV_POPULATE_WRITE 23
#endif

namespace istpu {

struct MappedPool {
  std::string name;
  uint8_t* base = nullptr;
  uint64_t size = 0;
};

class Client {
 public:
  ~Client() { close_conn(); }

  // returns 0 on success, negative errno-style on failure
  int connect_to(const char* host, int port, bool use_shm) {
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -2;
    if (connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      return -3;
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // HELLO: pid u32 | flags u32 -> pool table
    std::string body;
    Writer w(&body);
    w.put<uint32_t>(static_cast<uint32_t>(getpid()));
    w.put<uint32_t>(0);
    std::string resp;
    int32_t st = request(OP_HELLO, body, &resp);
    if (st != FINISH) return -4;
    if (!parse_pool_table(resp)) return -5;
    shm_ = use_shm;
    if (shm_ && !map_pools()) return -6;
    return 0;
  }

  void close_conn() {
    if (fd_ >= 0) {
      close(fd_);
      fd_ = -1;
    }
    for (auto& p : pools_) {
      if (p.base) munmap(p.base, p.size);
      p.base = nullptr;
    }
    pools_.clear();
  }

  // ---- batched zero-copy ops (reference: rdma_write_cache / rdma_read_cache) ----

  int32_t write_cache(const char* const* keys, const uint64_t* offsets, size_t n,
                      uint64_t block_size, const uint8_t* base) {
    std::lock_guard<std::mutex> g(mu_);
    if (shm_) {
      std::string body = pack_block_req(keys, n, block_size);
      std::string resp;
      int32_t st = request(OP_ALLOC_PUT, body, &resp);
      for (int retry = 0; retry < 20 && st == RETRY; retry++) {
        usleep(50000);
        st = request(OP_ALLOC_PUT, body, &resp);
      }
      if (st != FINISH) return st;
      size_t nd = resp.size() / sizeof(Desc);
      if (nd != n) return INTERNAL_ERROR;
      const Desc* descs = reinterpret_cast<const Desc*>(resp.data());
      for (size_t i = 0; i < n; i++) {
        uint8_t* dst = pool_ptr(descs[i].pool_idx, descs[i].offset);
        if (!dst) return INTERNAL_ERROR;
        std::memcpy(dst, base + offsets[i], block_size);
      }
      std::string commit;
      Writer w(&commit);
      put_keys(&w, keys, n);
      std::string resp2;
      return request(OP_COMMIT_PUT, commit, &resp2);
    }
    // inline path: frame + n*block_size payload
    std::string body = pack_block_req(keys, n, block_size);
    Header hdr{MAGIC, VERSION, OP_PUT_INLINE_BATCH, 0,
               static_cast<uint32_t>(body.size()), 0};
    if (!send_all(&hdr, sizeof(hdr)) || !send_all(body.data(), body.size()))
      return SYSTEM_ERROR;
    for (size_t i = 0; i < n; i++) {
      if (!send_all(base + offsets[i], block_size)) return SYSTEM_ERROR;
    }
    std::string resp;
    return read_resp(&resp);
  }

  int32_t read_cache(const char* const* keys, const uint64_t* offsets, size_t n,
                     uint64_t block_size, uint8_t* base) {
    std::lock_guard<std::mutex> g(mu_);
    if (shm_) {
      std::string body = pack_block_req(keys, n, block_size);
      std::string resp;
      int32_t st = request(OP_GET_DESC, body, &resp);
      if (st != FINISH) return st;
      size_t nd = resp.size() / sizeof(Desc);
      if (nd != n) return INTERNAL_ERROR;
      const Desc* descs = reinterpret_cast<const Desc*>(resp.data());
      for (size_t i = 0; i < n; i++) {
        uint8_t* src = pool_ptr(descs[i].pool_idx, descs[i].offset);
        if (!src) return INTERNAL_ERROR;
        std::memcpy(base + offsets[i], src, descs[i].size);
      }
      return FINISH;
    }
    std::string body = pack_block_req(keys, n, block_size);
    Header hdr{MAGIC, VERSION, OP_GET_INLINE_BATCH, 0,
               static_cast<uint32_t>(body.size()), 0};
    if (!send_all(&hdr, sizeof(hdr)) || !send_all(body.data(), body.size()))
      return SYSTEM_ERROR;
    RespHeader rh;
    if (!recv_all(&rh, sizeof(rh))) return SYSTEM_ERROR;
    if (rh.status != FINISH) {
      std::string drain(rh.body_len, 0);
      if (rh.body_len && !recv_all(drain.data(), rh.body_len)) return SYSTEM_ERROR;
      return rh.status;
    }
    std::vector<uint32_t> sizes(n);
    if (!recv_all(sizes.data(), 4 * n)) return SYSTEM_ERROR;
    for (size_t i = 0; i < n; i++) {
      if (!recv_all(base + offsets[i], sizes[i])) return SYSTEM_ERROR;
    }
    return FINISH;
  }

  // ---- single-key inline ----

  int32_t put_inline(const char* key, const uint8_t* data, uint64_t size) {
    std::lock_guard<std::mutex> g(mu_);
    std::string body;
    Writer w(&body);
    size_t klen = strlen(key);
    w.put<uint16_t>(static_cast<uint16_t>(klen));
    w.put_bytes(key, klen);
    w.put<uint64_t>(size);
    w.put_bytes(data, size);
    std::string resp;
    return request(OP_PUT_INLINE, body, &resp);
  }

  // out must hold cap bytes; *out_size gets stored size (fails if > cap)
  int32_t get_inline(const char* key, uint8_t* out, uint64_t cap,
                     uint64_t* out_size) {
    std::lock_guard<std::mutex> g(mu_);
    std::string body;
    Writer w(&body);
    put_keys(&w, &key, 1);
    Header hdr{MAGIC, VERSION, OP_GET_INLINE, 0,
               static_cast<uint32_t>(body.size()), 0};
    if (!send_all(&hdr, sizeof(hdr)) || !send_all(body.data(), body.size()))
      return SYSTEM_ERROR;
    RespHeader rh;
    if (!recv_all(&rh, sizeof(rh))) return SYSTEM_ERROR;
    if (rh.status != FINISH || rh.body_len > cap) {
      std::string drain(rh.body_len, 0);
      if (rh.body_len && !recv_all(drain.data(), rh.body_len)) return SYSTEM_ERROR;
      if (rh.status == FINISH) {  // caller buffer too small
        *out_size = rh.body_len;
        return INVALID_REQ;
      }
      return rh.status;
    }
    if (rh.body_len && !recv_all(out, rh.body_len)) return SYSTEM_ERROR;
    *out_size = rh.body_len;
    return FINISH;
  }

  // ---- metadata ----

  int32_t simple_i32(uint8_t op, const char* const* keys, size_t n, int32_t* out) {
    std::lock_guard<std::mutex> g(mu_);
    std::string body;
    Writer w(&body);
    put_keys(&w, keys, n);
    std::string resp;
    int32_t st = request(op, body, &resp);
    if (st == FINISH && resp.size() >= 4) std::memcpy(out, resp.data(), 4);
    return st;
  }

  int32_t purge(int32_t* out) {
    std::lock_guard<std::mutex> g(mu_);
    std::string resp;
    int32_t st = request(OP_PURGE, "", &resp);
    if (st == FINISH && resp.size() >= 4) std::memcpy(out, resp.data(), 4);
    return st;
  }

  int32_t evict(float mn, float mx) {
    std::lock_guard<std::mutex> g(mu_);
    std::string body;
    Writer w(&body);
    w.put<float>(mn);
    w.put<float>(mx);
    std::string resp;
    return request(OP_EVICT, body, &resp);
  }

  int32_t stats_json(char* buf, int cap) {
    std::lock_guard<std::mutex> g(mu_);
    std::string resp;
    int32_t st = request(OP_STATS, "", &resp);
    if (st != FINISH) return st;
    int n = std::min<int>(cap - 1, resp.size());
    std::memcpy(buf, resp.data(), n);
    buf[n] = 0;
    return FINISH;
  }

 private:
  static std::string pack_block_req(const char* const* keys, size_t n,
                                    uint64_t block_size) {
    std::string body;
    Writer w(&body);
    w.put<uint64_t>(block_size);
    put_keys(&w, keys, n);
    return body;
  }

  static void put_keys(Writer* w, const char* const* keys, size_t n) {
    w->put<uint32_t>(static_cast<uint32_t>(n));
    for (size_t i = 0; i < n; i++) {
      size_t klen = strlen(keys[i]);
      w->put<uint16_t>(static_cast<uint16_t>(klen));
      w->put_bytes(keys[i], klen);
    }
  }

  bool parse_pool_table(const std::string& resp) {
    Reader rd(reinterpret_cast<const uint8_t*>(resp.data()), resp.size());
    uint32_t n = rd.get<uint32_t>();
    if (!rd.ok()) return false;
    std::vector<MappedPool> table;
    for (uint32_t i = 0; i < n; i++) {
      uint16_t nlen = rd.get<uint16_t>();
      MappedPool p;
      if (!rd.ok() || !rd.get_bytes(&p.name, nlen)) return false;
      p.size = rd.get<uint64_t>();
      rd.get<uint64_t>();  // block_size (informational)
      if (!rd.ok()) return false;
      table.push_back(std::move(p));
    }
    // preserve existing mappings by name
    for (auto& np : table) {
      for (auto& op : pools_) {
        if (op.base && op.name == np.name) {
          np.base = op.base;
          op.base = nullptr;
        }
      }
    }
    for (auto& op : pools_) {
      if (op.base) munmap(op.base, op.size);
    }
    pools_ = std::move(table);
    return true;
  }

  bool map_pools() {
    for (auto& p : pools_) {
      if (p.base) continue;
      std::string path = "/dev/shm/" + p.name;
      int fd = open(path.c_str(), O_RDWR);
      if (fd < 0) return false;
      void* m = mmap(nullptr, p.size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
      close(fd);
      if (m == MAP_FAILED) return false;
      // server already populated the pages; this maps them into our page
      // table so the data path takes no minor faults
      madvise(m, p.size, MADV_POPULATE_WRITE);
      p.base = static_cast<uint8_t*>(m);
    }
    return true;
  }

  uint8_t* pool_ptr(uint32_t idx, uint64_t off) {
    if (idx >= pools_.size() || !pools_[idx].base) {
      // pool table grew (auto-extend): refresh + remap
      std::string resp;
      if (request(OP_POOLS, "", &resp) != FINISH || !parse_pool_table(resp) ||
          !map_pools() || idx >= pools_.size())
        return nullptr;
    }
    return pools_[idx].base + off;
  }

  bool send_all(const void* p, size_t n) {
    const char* b = static_cast<const char*>(p);
    while (n) {
      ssize_t r = send(fd_, b, n, MSG_NOSIGNAL);
      if (r <= 0) return false;
      b += r;
      n -= r;
    }
    return true;
  }

  bool recv_all(void* p, size_t n) {
    char* b = static_cast<char*>(p);
    while (n) {
      ssize_t r = recv(fd_, b, n, 0);
      if (r <= 0) return false;
      b += r;
      n -= r;
    }
    return true;
  }

  int32_t read_resp(std::string* body) {
    RespHeader rh;
    if (!recv_all(&rh, sizeof(rh))) return SYSTEM_ERROR;
    body->resize(rh.body_len);
    if (rh.body_len && !recv_all(body->data(), rh.body_len)) return SYSTEM_ERROR;
    return rh.status;
  }

  int32_t request(uint8_t op, const std::string& body, std::string* resp) {
    Header hdr{MAGIC, VERSION, op, 0, static_cast<uint32_t>(body.size()), 0};
    if (!send_all(&hdr, sizeof(hdr))) return SYSTEM_ERROR;
    if (!body.empty() && !send_all(body.data(), body.size())) return SYSTEM_ERROR;
    return read_resp(resp);
  }

  int fd_ = -1;
  bool shm_ = false;
  std::vector<MappedPool> pools_;
  std::mutex mu_;
};

Client* make_client() { return new Client(); }

}  // namespace istpu

// ---- C ABI for ctypes (infinistore_tpu/_native.py) ----

using istpu::Client;

extern "C" {

void* istpu_client_create() { return new Client(); }

int istpu_client_connect(void* h, const char* host, int port, int use_shm) {
  return static_cast<Client*>(h)->connect_to(host, port, use_shm != 0);
}

void istpu_client_close(void* h) { static_cast<Client*>(h)->close_conn(); }
void istpu_client_destroy(void* h) { delete static_cast<Client*>(h); }

int istpu_client_write_cache(void* h, const char* const* keys,
                             const uint64_t* offsets, int n,
                             uint64_t block_size, const void* base) {
  return static_cast<Client*>(h)->write_cache(
      keys, offsets, n, block_size, static_cast<const uint8_t*>(base));
}

int istpu_client_read_cache(void* h, const char* const* keys,
                            const uint64_t* offsets, int n, uint64_t block_size,
                            void* base) {
  return static_cast<Client*>(h)->read_cache(keys, offsets, n, block_size,
                                             static_cast<uint8_t*>(base));
}

int istpu_client_put_inline(void* h, const char* key, const void* data,
                            uint64_t size) {
  return static_cast<Client*>(h)->put_inline(
      key, static_cast<const uint8_t*>(data), size);
}

int istpu_client_get_inline(void* h, const char* key, void* out, uint64_t cap,
                            uint64_t* out_size) {
  return static_cast<Client*>(h)->get_inline(key, static_cast<uint8_t*>(out),
                                             cap, out_size);
}

int istpu_client_exist(void* h, const char* key, int* out) {
  int32_t v = 0;
  int st = static_cast<Client*>(h)->simple_i32(istpu::OP_EXIST, &key, 1, &v);
  *out = v;
  return st;
}

int istpu_client_match_last_index(void* h, const char* const* keys, int n,
                                  int* out) {
  int32_t v = -1;
  int st = static_cast<Client*>(h)->simple_i32(istpu::OP_MATCH_LAST_IDX, keys,
                                               n, &v);
  *out = v;
  return st;
}

int istpu_client_delete_keys(void* h, const char* const* keys, int n, int* out) {
  int32_t v = 0;
  int st = static_cast<Client*>(h)->simple_i32(istpu::OP_DELETE_KEYS, keys, n, &v);
  *out = v;
  return st;
}

int istpu_client_purge(void* h, int* out) {
  int32_t v = 0;
  int st = static_cast<Client*>(h)->purge(&v);
  *out = v;
  return st;
}

int istpu_client_evict(void* h, float mn, float mx) {
  return static_cast<Client*>(h)->evict(mn, mx);
}

int istpu_client_stats_json(void* h, char* buf, int cap) {
  return static_cast<Client*>(h)->stats_json(buf, cap);
}

}  // extern "C"
