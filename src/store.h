// KV store core: kv_map + intrusive LRU + pending (uncommitted) entries.
//
// C++ native runtime counterpart of infinistore_tpu/store.py; mirrors the
// reference server state (reference: src/infinistore.cpp:26-53 kv_map +
// lru_queue + MM) and its op semantics:
//  * entries visible only at commit (src/infinistore.cpp:405-418)
//  * reads touch LRU, 404 if any key missing (src/infinistore.cpp:612-634)
//  * eviction pops LRU until usage < min threshold (src/infinistore.cpp:223-234)
//  * on-demand thresholds 0.8/0.95 before allocation (src/infinistore.cpp:52-53)
//  * match_last_index binary search (src/infinistore.cpp:786-802)
#pragma once

#include <chrono>
#include <list>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

#include "mempool.h"
#include "protocol.h"

namespace istpu {

constexpr double kOnDemandMin = 0.8;
constexpr double kOnDemandMax = 0.95;
constexpr double kReadLeaseS = 5.0;

struct Entry {
  uint32_t pool_idx = 0;
  uint64_t offset = 0;
  uint64_t size = 0;
  double lease = 0.0;
  bool busy = false;  // an op is streaming payload into this pending region
  // alloc_put batch epoch: lets one map pass detect intra-batch duplicate
  // keys without a side dedup map (put-path hash traffic is the put/get
  // bandwidth gap)
  uint64_t batch = 0;
};

struct StoreStats {
  uint64_t puts = 0, gets = 0, hits = 0, misses = 0, evicted = 0;
  uint64_t bytes_in = 0, bytes_out = 0;
  uint64_t spilled = 0, promoted = 0;  // DRAM <-> disk tier traffic
  uint64_t contig_batches = 0;  // batch allocs served as one contiguous run
};

struct StoreConfig {
  uint64_t prealloc_bytes = 1ULL << 30;
  uint64_t block_bytes = 64 << 10;
  bool auto_increase = false;
  std::string shm_prefix;
  // second storage tier ("Historical KVCache in DRAM and SSD", reference
  // docs/source/design.rst:36): LRU-evicted entries spill to a
  // file-backed slab here and promote back on access.  Empty = DRAM only.
  std::string disk_tier_path;
  uint64_t disk_tier_bytes = 64ULL << 30;
  // "bitmap" (uniform-block runs) or "sizeclass" (pow2 classes, lazily
  // carved per-class pools) — see mempool.h Allocator
  std::string allocator = "bitmap";
};

// File-backed slab for the cold half of the cache hierarchy (counterpart
// of infinistore_tpu/store.py DiskTier).  Entries span ceil(size/block)
// CONSECUTIVE slots (DRAM regions are contiguous multi-block runs);
// allocation is first-fit over a sorted free-slot set; when the slab
// fills, the coldest spilled entries are dropped for good.  No fsync: a
// cache tier, not a database.
class DiskTier {
 public:
  DiskTier(const std::string& dir, uint64_t capacity_bytes, uint64_t block);
  ~DiskTier();

  bool put(const std::string& key, const uint8_t* data, uint64_t size);
  // reads into out (resized); false if absent
  bool get(const std::string& key, std::vector<uint8_t>* out) const;
  bool contains(const std::string& key) const { return index_.count(key) != 0; }
  bool pop(const std::string& key);  // true when an entry was removed
  size_t clear();
  size_t entries() const { return index_.size(); }
  uint64_t used_bytes() const { return bytes_; }
  uint64_t dropped() const { return dropped_; }

 private:
  struct Rec {
    uint64_t slot = 0, size = 0;
    std::list<std::string>::iterator lru_it;
  };
  uint64_t slots_for(uint64_t size) const {
    return size ? (size + block_ - 1) / block_ : 1;
  }
  void release_run(uint64_t slot, uint64_t size);
  // -1 when no run can be made (after dropping everything)
  int64_t alloc_run(uint64_t n);
  int64_t find_run(uint64_t n);

  std::string path_;
  int fd_ = -1;
  uint64_t block_;
  uint64_t capacity_slots_;
  std::unordered_map<std::string, Rec> index_;
  std::list<std::string> lru_;  // front = oldest spill
  std::set<uint64_t> free_;     // sorted free slots
  uint64_t next_slot_ = 0;
  uint64_t bytes_ = 0;
  uint64_t dropped_ = 0;
};

class Store {
 public:
  explicit Store(const StoreConfig& cfg);

  // ---- zero-copy batched ops ----
  Status alloc_put(const std::vector<std::string>& keys, uint64_t block_size,
                   std::vector<Desc>* descs);
  void abort_put(const std::vector<std::string>& keys);
  Status commit_put(const std::vector<std::string>& keys, int32_t* committed);
  Status get_desc(const std::vector<std::string>& keys, uint64_t block_size,
                  std::vector<Desc>* descs);

  // ---- inline ops ----
  Status put_inline(const std::string& key, const uint8_t* data, uint64_t size);
  const Entry* get_inline(const std::string& key);  // touches LRU; null if miss

  // ---- metadata ----
  // present = retrievable from EITHER tier: a spilled entry still serves
  // reads via promotion, so exist / the prefix match advertise it
  bool exist(const std::string& key) const {
    return kv_.count(key) != 0 || (disk_ && disk_->contains(key));
  }
  int32_t match_last_index(const std::vector<std::string>& keys) const;
  int32_t delete_keys(const std::vector<std::string>& keys);
  int32_t purge();
  int64_t evict(double min_threshold, double max_threshold);
  // Region pinning: while a region's pages are queued as zero-copy response
  // segments, any free of it (delete/evict/overwrite/lease expiry) is parked
  // as a zombie and executed at the final unpin.  Unlike the time-based
  // lease this cannot lapse under a stalled receiver.
  void pin(const std::vector<Desc>& descs);
  void unpin(const std::vector<Desc>& descs);

  uint8_t* view(uint32_t pool_idx, uint64_t offset) { return mm_.view(pool_idx, offset); }
  double usage() const { return mm_.usage(); }
  size_t kvmap_len() const { return kv_.size(); }
  size_t pending_len() const { return pending_.size(); }
  const MM& mm() const { return mm_; }
  const StoreStats& stats() const { return stats_; }
  std::string stats_json() const;
  Entry* pending_entry(const std::string& key);

 private:
  using LruList = std::list<std::string>;  // front = LRU, back = MRU
  struct Slot {
    Entry e;
    LruList::iterator lru_it;
  };

  void free_entry(const Entry& e);  // respects pins (zombie until unpin)
  // pull a spilled entry back into a DRAM pool (may evict-and-spill
  // colder keys); nullptr when absent on disk or DRAM can't fit it
  Entry* promote(const std::string& key);
  // delete/purge/overwrite of a leased entry must not yank pool memory out
  // from under an in-flight shm read: the key disappears immediately, the
  // region is freed once the lease expires
  void free_or_defer(const Entry& e, double now);
  void reap_deferred(double now);
  void insert_committed(const std::string& key, const Entry& e);
  void touch(Slot& s, const std::string& key);
  bool allocate(uint64_t size, size_t n, std::vector<Region>* out);
  int64_t pressure_evict(size_t n);  // class-blind LRU pops (sizeclass)
  static double now();

  StoreConfig cfg_;
  MM mm_;
  std::unordered_map<std::string, Slot> kv_;
  // same mapped type as kv_ so commit_put can SPLICE nodes between the two
  // maps (extract/insert: no per-key node allocation on the put hot path);
  // lru_it is unset while pending
  std::unordered_map<std::string, Slot> pending_;
  LruList lru_;
  uint64_t alloc_epoch_ = 0;
  StoreStats stats_;
  std::vector<std::pair<double, Entry>> deferred_;  // (lease expiry, region)
  using RegionId = std::pair<uint32_t, uint64_t>;   // (pool_idx, offset)
  std::map<RegionId, int> pins_;                    // outstanding send refs
  std::map<RegionId, uint64_t> zombies_;            // freed-while-pinned: size
  std::unique_ptr<DiskTier> disk_;                  // optional spill tier
};

}  // namespace istpu
