#include "store.h"

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <cstring>
#include <stdexcept>

namespace istpu {

static std::string rand_prefix() {
  char buf[64];
  snprintf(buf, sizeof(buf), "istpu_%d_%08x", getpid(),
           static_cast<unsigned>(std::chrono::steady_clock::now().time_since_epoch().count()));
  return buf;
}

// ---- DiskTier ----

static void mkdirs(const std::string& dir) {
  // recursive create (os.makedirs parity); EEXIST is fine at every level
  for (size_t i = 1; i <= dir.size(); i++) {
    if (i == dir.size() || dir[i] == '/')
      mkdir(dir.substr(0, i).c_str(), 0777);
  }
}

DiskTier::DiskTier(const std::string& dir, uint64_t capacity_bytes,
                   uint64_t block)
    : block_(block),
      capacity_slots_(capacity_bytes / block ? capacity_bytes / block : 1) {
  mkdirs(dir);
  path_ = dir + "/istpu_disk_tier.dat";
  fd_ = open(path_.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (fd_ < 0) {
    // fail LOUDLY at startup (python-backend parity): a tier the operator
    // asked for that silently drops every spill is worse than no server
    throw std::runtime_error("disk tier: cannot open " + path_ + ": " +
                             std::strerror(errno));
  }
}

DiskTier::~DiskTier() {
  if (fd_ >= 0) close(fd_);
  unlink(path_.c_str());
}

void DiskTier::release_run(uint64_t slot, uint64_t size) {
  for (uint64_t s = slot; s < slot + slots_for(size); s++) free_.insert(s);
}

int64_t DiskTier::find_run(uint64_t n) {
  // first-fit over the sorted free set
  uint64_t count = 0, start = 0, prev = 0;
  for (uint64_t s : free_) {
    if (count && s == prev + 1) {
      count++;
    } else {
      start = s;
      count = 1;
    }
    prev = s;
    if (count == n) {
      for (uint64_t i = start; i < start + n; i++) free_.erase(i);
      return static_cast<int64_t>(start);
    }
  }
  return -1;
}

int64_t DiskTier::alloc_run(uint64_t n) {
  if (n > capacity_slots_) return -1;
  for (;;) {
    int64_t start = find_run(n);
    if (start >= 0) return start;
    if (next_slot_ + n <= capacity_slots_) {
      start = static_cast<int64_t>(next_slot_);
      next_slot_ += n;
      return start;
    }
    if (index_.empty()) return -1;
    // slab full: the coldest spilled entries leave the hierarchy until a
    // big-enough run frees up
    const std::string victim = lru_.front();
    auto it = index_.find(victim);
    bytes_ -= it->second.size;
    dropped_++;
    release_run(it->second.slot, it->second.size);
    lru_.pop_front();
    index_.erase(it);
  }
}

bool DiskTier::put(const std::string& key, const uint8_t* data, uint64_t size) {
  if (fd_ < 0) return false;
  pop(key);  // an old copy's run goes back to the free set
  int64_t slot = alloc_run(slots_for(size));
  if (slot < 0) return false;
  if (pwrite(fd_, data, size, static_cast<off_t>(slot) * block_) !=
      static_cast<ssize_t>(size)) {
    release_run(static_cast<uint64_t>(slot), size);
    return false;  // disk full / IO error: entry simply doesn't spill
  }
  lru_.push_back(key);
  index_[key] = Rec{static_cast<uint64_t>(slot), size, std::prev(lru_.end())};
  bytes_ += size;
  return true;
}

bool DiskTier::get(const std::string& key, std::vector<uint8_t>* out) const {
  auto it = index_.find(key);
  if (it == index_.end()) return false;
  out->resize(it->second.size);
  return pread(fd_, out->data(), it->second.size,
               static_cast<off_t>(it->second.slot) * block_) ==
         static_cast<ssize_t>(it->second.size);
}

bool DiskTier::pop(const std::string& key) {
  auto it = index_.find(key);
  if (it == index_.end()) return false;
  bytes_ -= it->second.size;
  release_run(it->second.slot, it->second.size);
  lru_.erase(it->second.lru_it);
  index_.erase(it);
  return true;
}

size_t DiskTier::clear() {
  size_t n = index_.size();
  index_.clear();
  lru_.clear();
  free_.clear();
  next_slot_ = 0;
  bytes_ = 0;
  return n;
}

// ---- Store ----

Store::Store(const StoreConfig& cfg)
    : cfg_(cfg),
      mm_(cfg.prealloc_bytes, cfg.block_bytes,
          cfg.shm_prefix.empty() ? rand_prefix() : cfg.shm_prefix,
          cfg.allocator == "sizeclass" ? Allocator::kSizeClass
                                       : Allocator::kBitmap) {
  // pre-size the hash tables: a serving round puts/gets thousands of page
  // keys and a mid-batch rehash stalls the single-threaded event loop
  kv_.reserve(1 << 15);
  pending_.reserve(1 << 12);
  if (!cfg.disk_tier_path.empty())
    disk_ = std::make_unique<DiskTier>(cfg.disk_tier_path,
                                       cfg.disk_tier_bytes, cfg.block_bytes);
}

double Store::now() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void Store::free_entry(const Entry& e) {
  RegionId id{e.pool_idx, e.offset};
  if (pins_.count(id)) {
    zombies_[id] = e.size;  // blocks stay allocated until the final unpin
    return;
  }
  mm_.deallocate(e.pool_idx, e.offset, e.size);
}

void Store::pin(const std::vector<Desc>& descs) {
  for (const auto& d : descs) pins_[{d.pool_idx, d.offset}]++;
}

void Store::unpin(const std::vector<Desc>& descs) {
  for (const auto& d : descs) {
    RegionId id{d.pool_idx, d.offset};
    auto it = pins_.find(id);
    if (it == pins_.end()) continue;
    if (--it->second == 0) {
      pins_.erase(it);
      auto z = zombies_.find(id);
      if (z != zombies_.end()) {
        mm_.deallocate(id.first, id.second, z->second);
        zombies_.erase(z);
      }
    }
  }
}

void Store::free_or_defer(const Entry& e, double now) {
  if (e.lease > now)
    deferred_.emplace_back(e.lease, e);
  else
    free_entry(e);
}

void Store::reap_deferred(double now) {
  size_t w = 0;
  for (size_t i = 0; i < deferred_.size(); i++) {
    if (deferred_[i].first <= now)
      free_entry(deferred_[i].second);
    else
      deferred_[w++] = deferred_[i];
  }
  deferred_.resize(w);
}

void Store::touch(Slot& s, const std::string& key) {
  lru_.erase(s.lru_it);
  lru_.push_back(key);
  s.lru_it = std::prev(lru_.end());
}

void Store::insert_committed(const std::string& key, const Entry& e) {
  auto it = kv_.find(key);
  if (it != kv_.end()) {  // overwrite: old region freed when safe
    free_or_defer(it->second.e, now());
    lru_.erase(it->second.lru_it);
    kv_.erase(it);
  }
  // a fresh commit supersedes any spilled copy (stale data must never
  // promote back over it)
  if (disk_) disk_->pop(key);
  lru_.push_back(key);
  kv_.emplace(key, Slot{e, std::prev(lru_.end())});
}

Entry* Store::promote(const std::string& key) {
  if (!disk_) return nullptr;
  std::vector<uint8_t> data;
  if (!disk_->get(key, &data)) return nullptr;
  std::vector<Region> regions;
  if (!allocate(data.size(), 1, &regions)) return nullptr;
  std::memcpy(mm_.view(regions[0].pool_idx, regions[0].offset), data.data(),
              data.size());
  // insert_committed drops the disk copy (its supersede rule)
  insert_committed(key, Entry{regions[0].pool_idx, regions[0].offset,
                              data.size()});
  stats_.promoted++;
  return &kv_.find(key)->second.e;
}

int64_t Store::evict(double min_threshold, double max_threshold) {
  int64_t evicted = 0;
  reap_deferred(now());
  if (mm_.usage() >= max_threshold) {
    double t = now();
    size_t rotated = 0;
    while (mm_.usage() >= min_threshold && !lru_.empty()) {
      const std::string key = lru_.front();
      auto it = kv_.find(key);
      if (it == kv_.end()) {  // should not happen; keep structures in sync
        lru_.pop_front();
        continue;
      }
      if (it->second.e.lease > t) {
        // leased for an in-flight shm read; rotate past it
        touch(it->second, key);
        if (++rotated >= kv_.size()) break;
        continue;
      }
      if (disk_) {
        // spill before the blocks are reused: not leased (checked above),
        // so the bytes are stable
        const Entry& e = it->second.e;
        if (disk_->put(key, mm_.view(e.pool_idx, e.offset), e.size))
          stats_.spilled++;
      }
      free_entry(it->second.e);
      lru_.pop_front();
      kv_.erase(it);
      evicted++;
    }
  }
  stats_.evicted += evicted;
  return evicted;
}

int64_t Store::pressure_evict(size_t n) {
  // LRU pops that ignore the global usage gate: the size-classed
  // allocator can be FULL in one class while global usage looks low
  // (the threshold evict never fires), so allocation failure pops LRU
  // entries directly — eventually reaching the full class's own
  // entries.  Leased entries rotate past; spill semantics match evict().
  int64_t evicted = 0;
  double t = now();
  size_t rotated = 0;
  while (static_cast<size_t>(evicted) < n && !lru_.empty() &&
         rotated < kv_.size()) {
    const std::string key = lru_.front();
    auto it = kv_.find(key);
    if (it == kv_.end()) {
      lru_.pop_front();
      continue;
    }
    if (it->second.e.lease > t) {
      touch(it->second, key);
      rotated++;
      continue;
    }
    if (disk_) {
      const Entry& e = it->second.e;
      if (disk_->put(key, mm_.view(e.pool_idx, e.offset), e.size))
        stats_.spilled++;
    }
    free_entry(it->second.e);
    lru_.pop_front();
    kv_.erase(it);
    evicted++;
  }
  stats_.evicted += evicted;
  return evicted;
}

bool Store::allocate(uint64_t size, size_t n, std::vector<Region>* out) {
  // on-demand evict + allocate + auto-extend retry (src/infinistore.cpp:437-452).
  // Batches first try ONE contiguous run so descriptors coalesce into
  // bulk memcpys client-side; fragmentation falls back per-region.
  evict(kOnDemandMin, kOnDemandMax);
  if (n > 1 && mm_.allocate_contiguous(size, n, out)) {
    stats_.contig_batches++;
    return true;
  }
  if (mm_.allocate(size, n, out)) return true;
  if (cfg_.auto_increase && mm_.need_extend) {
    mm_.add_pool();
    mm_.need_extend = false;
    if (n > 1 && mm_.allocate_contiguous(size, n, out)) {
      stats_.contig_batches++;
      return true;
    }
    if (mm_.allocate(size, n, out)) return true;
  }
  if (cfg_.allocator == "sizeclass" && mm_.eviction_could_satisfy(size, n)) {
    // class-pressure eviction (see pressure_evict); the guard keeps one
    // unsatisfiable request from draining the whole cache and failing
    while (pressure_evict(8) > 0) {
      if (mm_.allocate(size, n, out)) return true;
    }
  }
  return false;
}

Status Store::alloc_put(const std::vector<std::string>& keys, uint64_t block_size,
                        std::vector<Desc>* descs) {
  // ONE hash pass covers dedup + busy-check + slot lookup (the put path's
  // map traffic dominated the put/get bandwidth gap): each key is
  // try_emplace'd once; an existing slot already stamped with THIS batch's
  // epoch is an intra-batch duplicate (two regions for one map slot), a
  // busy slot is an in-flight inline write.  The error paths roll back the
  // placeholders they inserted BEFORE any region was allocated or freed,
  // so RETRY / INVALID_REQ stay side-effect free.  (Pointers into an
  // unordered_map survive rehash; only iterators die.)
  const uint64_t epoch = ++alloc_epoch_;
  struct Ref { Slot* slot; bool existed; };
  std::vector<Ref> refs;
  refs.reserve(keys.size());
  auto rollback = [&]() {
    for (size_t i = 0; i < refs.size(); i++)
      if (!refs[i].existed) pending_.erase(keys[i]);
  };
  for (const auto& k : keys) {
    auto [it, inserted] = pending_.try_emplace(k);
    Slot& s = it->second;
    if (!inserted && (s.e.busy || s.e.batch == epoch)) {
      const bool busy = s.e.busy;
      rollback();
      return busy ? RETRY : INVALID_REQ;
    }
    s.e.batch = epoch;
    refs.push_back({&s, !inserted});
  }
  std::vector<Region> regions;
  regions.reserve(keys.size());
  if (!allocate(block_size, keys.size(), &regions)) {
    rollback();
    return OUT_OF_MEMORY;
  }
  descs->reserve(keys.size());
  for (size_t i = 0; i < keys.size(); i++) {
    Slot& s = *refs[i].slot;
    if (refs[i].existed) free_entry(s.e);  // pending overwrite: old region out
    s.e = Entry{regions[i].pool_idx, regions[i].offset, block_size};
    s.e.batch = epoch;
    descs->push_back({regions[i].pool_idx, regions[i].offset, block_size});
  }
  return FINISH;
}

void Store::abort_put(const std::vector<std::string>& keys) {
  for (const auto& k : keys) {
    auto it = pending_.find(k);
    if (it != pending_.end()) {
      free_entry(it->second.e);
      pending_.erase(it);
    }
  }
}

Status Store::commit_put(const std::vector<std::string>& keys, int32_t* committed) {
  *committed = 0;
  for (const auto& k : keys) {
    auto it = pending_.find(k);
    if (it == pending_.end()) continue;
    // splice the node from pending_ into kv_ (extract/insert moves the
    // allocated node: no new allocation, no key copy on the put hot path)
    auto node = pending_.extract(it);
    Slot& s = node.mapped();
    s.e.busy = false;
    stats_.puts++;
    stats_.bytes_in += s.e.size;
    (*committed)++;
    auto old = kv_.find(k);
    if (old != kv_.end()) {  // overwrite: old region freed when safe
      free_or_defer(old->second.e, now());
      lru_.erase(old->second.lru_it);
      kv_.erase(old);
    }
    lru_.push_back(k);
    s.lru_it = std::prev(lru_.end());
    kv_.insert(std::move(node));
  }
  return *committed == static_cast<int32_t>(keys.size()) ? FINISH : INVALID_REQ;
}

Status Store::get_desc(const std::vector<std::string>& keys, uint64_t block_size,
                       std::vector<Desc>* descs) {
  // two passes on purpose: promoting a spilled batchmate allocates, which
  // can evict — leasing each key the moment it checks out keeps the
  // evictor's hands off earlier keys of the SAME batch, so the
  // descriptors built in pass 2 can never go stale mid-request
  double t = now();
  for (const auto& k : keys) {
    auto it = kv_.find(k);
    Entry* e = it != kv_.end() ? &it->second.e : promote(k);
    if (e == nullptr) {
      stats_.misses++;
      return KEY_NOT_FOUND;
    }
    if (block_size && e->size > block_size) return INVALID_REQ;
    e->lease = t + kReadLeaseS;
  }
  descs->reserve(keys.size());
  for (const auto& k : keys) {
    auto& s = kv_.find(k)->second;
    touch(s, k);
    stats_.gets++;
    stats_.hits++;
    stats_.bytes_out += s.e.size;
    descs->push_back({s.e.pool_idx, s.e.offset, s.e.size});
  }
  return FINISH;
}

Status Store::put_inline(const std::string& key, const uint8_t* data, uint64_t size) {
  std::vector<Region> regions;
  if (!allocate(size, 1, &regions)) return OUT_OF_MEMORY;
  std::memcpy(mm_.view(regions[0].pool_idx, regions[0].offset), data, size);
  insert_committed(key, Entry{regions[0].pool_idx, regions[0].offset, size});
  stats_.puts++;
  stats_.bytes_in += size;
  return FINISH;
}

const Entry* Store::get_inline(const std::string& key) {
  auto it = kv_.find(key);
  if (it == kv_.end()) {
    if (promote(key) == nullptr) {
      stats_.misses++;
      return nullptr;
    }
    it = kv_.find(key);
  }
  touch(it->second, key);
  stats_.gets++;
  stats_.hits++;
  stats_.bytes_out += it->second.e.size;
  return &it->second.e;
}

int32_t Store::match_last_index(const std::vector<std::string>& keys) const {
  // binary search: assumes present keys form a prefix (src/infinistore.cpp:786-802)
  int32_t left = 0, right = static_cast<int32_t>(keys.size());
  while (left < right) {
    int32_t mid = (left + right) / 2;
    if (exist(keys[mid]))  // either tier counts (spilled entries serve reads)
      left = mid + 1;
    else
      right = mid;
  }
  return left - 1;
}

int32_t Store::delete_keys(const std::vector<std::string>& keys) {
  int32_t count = 0;
  double t = now();
  reap_deferred(t);
  for (const auto& k : keys) {
    bool on_disk = disk_ && disk_->pop(k);
    auto it = kv_.find(k);
    if (it == kv_.end()) {
      if (on_disk) count++;
      continue;
    }
    free_or_defer(it->second.e, t);
    lru_.erase(it->second.lru_it);
    kv_.erase(it);
    count++;
  }
  return count;
}

int32_t Store::purge() {
  int32_t n = static_cast<int32_t>(kv_.size());
  double t = now();
  reap_deferred(t);
  for (auto& [k, s] : kv_) free_or_defer(s.e, t);
  kv_.clear();
  lru_.clear();
  // keep regions an op is actively streaming into; free the rest
  std::unordered_map<std::string, Slot> keep;
  for (auto& [k, s] : pending_) {
    if (s.e.busy)
      keep.emplace(k, s);
    else
      free_entry(s.e);
  }
  pending_ = std::move(keep);
  if (disk_) n += static_cast<int32_t>(disk_->clear());
  return n;
}

Entry* Store::pending_entry(const std::string& key) {
  auto it = pending_.find(key);
  return it == pending_.end() ? nullptr : &it->second.e;
}

std::string Store::stats_json() const {
  char buf[768];
  int n = snprintf(buf, sizeof(buf),
           "{\"kvmap_len\": %zu, \"pending\": %zu, \"usage\": %.6f, "
           "\"pools\": %zu, \"block_size\": %llu, \"puts\": %llu, "
           "\"gets\": %llu, \"hits\": %llu, \"misses\": %llu, "
           "\"evicted\": %llu, \"bytes_in\": %llu, \"bytes_out\": %llu, "
           "\"contig_batches\": %llu",
           kv_.size(), pending_.size(), mm_.usage(), mm_.pools().size(),
           static_cast<unsigned long long>(mm_.block_size()),
           static_cast<unsigned long long>(stats_.puts),
           static_cast<unsigned long long>(stats_.gets),
           static_cast<unsigned long long>(stats_.hits),
           static_cast<unsigned long long>(stats_.misses),
           static_cast<unsigned long long>(stats_.evicted),
           static_cast<unsigned long long>(stats_.bytes_in),
           static_cast<unsigned long long>(stats_.bytes_out),
           static_cast<unsigned long long>(stats_.contig_batches));
  if (disk_) {
    n += snprintf(buf + n, sizeof(buf) - n,
                  ", \"disk_entries\": %zu, \"disk_bytes\": %llu, "
                  "\"disk_spilled\": %llu, \"disk_promoted\": %llu, "
                  "\"disk_dropped\": %llu",
                  disk_->entries(),
                  static_cast<unsigned long long>(disk_->used_bytes()),
                  static_cast<unsigned long long>(stats_.spilled),
                  static_cast<unsigned long long>(stats_.promoted),
                  static_cast<unsigned long long>(disk_->dropped()));
  }
  snprintf(buf + n, sizeof(buf) - n, "}");
  return buf;
}

}  // namespace istpu
