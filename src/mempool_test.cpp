// Native-runtime mempool unit checks, mirrored from tests/test_mempool.py
// (the two MMs are parity-tested as equivalents; this binary keeps the
// C++ side honest where the wire tests can't reach — e.g. the
// carve-index-after-reclassify regression).  Run by
// tests/test_mempool.py::test_native_mempool_unit via `make test` (the
// Makefile builds it next to the library).
#include <cassert>
#include <cstdio>
#include <cstring>

#include "mempool.h"

using istpu::Allocator;
using istpu::MM;
using istpu::Region;

static void test_sizeclass_reclassify_index() {
  // 256 KB budget, 4 KB min class
  MM mm(1 << 18, 4096, "istpu_0_mmtest_a", Allocator::kSizeClass);
  std::vector<Region> a, b, filler, c;
  assert(mm.allocate(4096, 1, &a));           // pool 0: 4 KB class
  assert(mm.allocate(8192, 1, &b));           // pool 1: 8 KB class
  assert(mm.pools().size() == 2);
  mm.deallocate(a[0].pool_idx, a[0].offset, 4096);
  // soak every remaining 4 KB block so fresh budget is gone
  while (mm.allocate(4096, 1, &filler)) {
  }
  mm.need_extend = false;
  // drain pool 0 again so it is EMPTY and reclassifiable
  for (const auto& r : filler) mm.deallocate(r.pool_idx, r.offset, 4096);
  filler.clear();
  // 16 KB class: only satisfiable by reclassifying an EMPTY pool —
  // the recorded index must be that pool's REAL slot
  assert(mm.allocate(16 << 10, 1, &c));
  const Region& r = c[0];
  assert(mm.pools()[r.pool_idx]->block_size() == (16u << 10));
  // bytes written through the recorded region must not alias pool 1
  std::memcpy(mm.view(r.pool_idx, r.offset), "REGRTEST", 8);
  assert(std::memcmp(mm.view(b[0].pool_idx, b[0].offset), "REGRTEST", 8) !=
         0);
  mm.deallocate(r.pool_idx, r.offset, 16 << 10);
  assert(mm.pools()[r.pool_idx]->allocated_blocks() == 0);
}

static void test_sizeclass_guards() {
  MM mm(1 << 18, 4096, "istpu_0_mmtest_b", Allocator::kSizeClass);
  std::vector<Region> out;
  assert(!mm.allocate(0, 1, &out));                 // zero size
  assert(!mm.allocate((1ULL << 50) + 1, 1, &out));  // absurd size
  assert(mm.eviction_could_satisfy(4096, 64));
  assert(!mm.eviction_could_satisfy(4096, 65));     // beyond budget
  assert(!mm.eviction_could_satisfy(1 << 20, 1));   // class > budget
}

static void test_bitmap_roundtrip() {
  MM mm(1 << 18, 4096, "istpu_0_mmtest_c", Allocator::kBitmap);
  std::vector<Region> out;
  assert(mm.allocate(10000, 3, &out));  // rounds to 3 blocks each
  assert(out.size() == 3);
  std::memcpy(mm.view(out[1].pool_idx, out[1].offset), "bitmapOK", 8);
  assert(std::memcmp(mm.view(out[1].pool_idx, out[1].offset), "bitmapOK",
                     8) == 0);
  for (const auto& r : out) mm.deallocate(r.pool_idx, r.offset, 10000);
  assert(mm.usage() == 0.0);
}

int main() {
  setenv("ISTPU_NO_PREFAULT", "1", 1);  // tiny pools; skip the pin thread
  test_sizeclass_reclassify_index();
  test_sizeclass_guards();
  test_bitmap_roundtrip();
  std::printf("mempool_test: OK\n");
  return 0;
}
