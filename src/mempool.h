// Slab memory pools over POSIX shared memory - C++ native runtime.
//
// TPU-native counterpart of the reference's RDMA-registered pinned pool
// (reference: src/mempool.{h,cpp}): fixed-block bitmap allocator, multi-pool
// manager with 10 GB auto-extend.  Pools are /dev/shm segments so local
// clients (the inference engine on the same TPU-VM host) map them and move
// KV blocks with plain memcpy - the GPUDirect/RDMA analog.  Pages are
// pre-faulted at creation (MADV_POPULATE_WRITE), the moral equivalent of
// ibv_reg_mr's pin: the data path never takes a tmpfs first-touch fault.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <thread>
#include <vector>

namespace istpu {

constexpr uint64_t kExtendPoolSize = 10ULL << 30;  // reference: src/mempool.h:12
// Size-class pools carve in chunks of budget/kCarveDivisor (MUST match
// the Python MM.CARVE_DIVISOR — the two runtimes' carve behavior is
// parity-tested as equivalents).
constexpr uint64_t kCarveDivisor = 4;
// Reject absurd wire-controlled sizes before class math: pow2ceil would
// overflow (and loop) past 2^62, and no real store object approaches it.
constexpr uint64_t kMaxAllocSize = 1ULL << 50;

class Pool {
 public:
  Pool(const std::string& name, uint64_t pool_size, uint64_t block_size);
  ~Pool();
  Pool(const Pool&) = delete;
  Pool& operator=(const Pool&) = delete;

  // Returns byte offset into the pool, or -1.  size is rounded up to blocks.
  int64_t allocate(uint64_t size);
  void deallocate(uint64_t offset, uint64_t size);
  // Repurpose an EMPTY pool for another size class (sizeclass MM) —
  // floor division; a non-multiple tail is wasted until reclassified.
  void reclassify(uint64_t new_block_size);

  uint8_t* data() const { return base_; }
  const std::string& name() const { return name_; }
  uint64_t pool_size() const { return pool_size_; }
  uint64_t block_size() const { return block_size_; }
  uint64_t total_blocks() const { return total_blocks_; }
  uint64_t allocated_blocks() const { return allocated_blocks_; }
  bool prefault_done() const { return prefault_done_.load(); }

 private:
  void prefault_bg();  // chunked MADV_POPULATE_WRITE off-thread
  int64_t find_run(uint64_t k);  // first free run of k blocks, or -1

  std::string name_;
  std::string path_;
  uint64_t pool_size_;
  uint64_t block_size_;
  uint64_t total_blocks_;
  uint64_t allocated_blocks_ = 0;
  uint64_t rover_ = 0;
  uint8_t* base_ = nullptr;
  std::vector<uint64_t> bitmap_;  // bit set => block in use
  std::atomic<bool> closing_{false};
  std::atomic<bool> prefault_done_{false};
  std::thread prefault_thread_;
};

// Remove /dev/shm/istpu_<pid>_* segments whose owning pid is dead (a
// SIGKILL'd server never unlinks; new servers reclaim at startup).
int sweep_stale_segments();

struct Region {
  uint32_t pool_idx;
  uint64_t offset;
};

// Allocator strategy (reference design.rst:52 "bitmap or jemalloc"):
// kBitmap = uniform-block run allocator; kSizeClass = pow2 size classes
// with lazily carved per-class pools (the jemalloc-shaped option — less
// internal fragmentation when mixed page sizes share one store).
enum class Allocator { kBitmap, kSizeClass };

class MM {
 public:
  MM(uint64_t pool_size, uint64_t block_size, const std::string& name_prefix,
     Allocator allocator = Allocator::kBitmap);
  ~MM() = default;

  // Bitmap: adds a pool.  Size-class: grants BUDGET (returns nullptr);
  // the class that hit the wall carves its pool on the retry.
  Pool* add_pool(uint64_t pool_size = kExtendPoolSize);

  // All-or-nothing batch allocate of n regions of `size` bytes each
  // (reference: src/mempool.cpp MM::allocate's callback-per-region loop).
  bool allocate(uint64_t size, size_t n, std::vector<Region>* out);

  // Best-effort: n regions of `size` bytes as ONE contiguous run in one
  // pool (region i at base + i*stride, stride = size rounded up to the
  // pool's block size), so batch-put descriptors merge into bulk memcpys
  // client-side.  Never sets need_extend; false = caller falls back to
  // the per-region allocate().
  bool allocate_contiguous(uint64_t size, size_t n, std::vector<Region>* out);
  void deallocate(uint32_t pool_idx, uint64_t offset, uint64_t size);

  // sizeclass only: could freeing committed entries EVER make
  // allocate(size, n) succeed?  Guards the store's pressure-evict loop.
  bool eviction_could_satisfy(uint64_t size, size_t n) const;

  uint8_t* view(uint32_t pool_idx, uint64_t offset) const {
    return pools_[pool_idx]->data() + offset;
  }
  double usage() const;
  uint64_t block_size() const { return block_size_; }
  const std::vector<std::unique_ptr<Pool>>& pools() const { return pools_; }

  bool need_extend = false;

 private:
  // Size-class pool for `cls`: reclassify an empty pool (keeps its
  // ORIGINAL index) or carve fresh budget (appends).  Returns the
  // pool's index, or -1 — callers must use it, never pools_.size()-1.
  int64_t carve(uint64_t cls);
  uint64_t class_of(uint64_t size) const;

  Allocator allocator_;
  uint64_t block_size_;
  std::string name_prefix_;
  std::vector<std::unique_ptr<Pool>> pools_;
  uint64_t budget_ = 0;  // size-class mode only
  uint64_t carved_ = 0;
};

}  // namespace istpu
