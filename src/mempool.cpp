#include "mempool.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <stdexcept>

#ifndef MADV_POPULATE_WRITE
#define MADV_POPULATE_WRITE 23  // linux >= 5.14
#endif

namespace istpu {

static uint64_t round_up(uint64_t x, uint64_t align) {
  return (x + align - 1) / align * align;
}

Pool::Pool(const std::string& name, uint64_t pool_size, uint64_t block_size)
    : name_(name),
      path_("/dev/shm/" + name),
      pool_size_(pool_size),
      block_size_(block_size),
      total_blocks_(pool_size / block_size),
      bitmap_((pool_size / block_size + 63) / 64, 0) {
  if (pool_size % block_size != 0) throw std::invalid_argument("pool_size % block_size");
  int fd = open(path_.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) throw std::runtime_error("shm open failed: " + path_);
  if (ftruncate(fd, static_cast<off_t>(pool_size)) != 0) {
    close(fd);
    unlink(path_.c_str());
    throw std::runtime_error("ftruncate failed: " + path_);
  }
  void* p = mmap(nullptr, pool_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (p == MAP_FAILED) {
    unlink(path_.c_str());
    throw std::runtime_error("mmap failed: " + path_);
  }
  base_ = static_cast<uint8_t*>(p);
  // pre-fault (the ibv_reg_mr-pin analog); fall back to a touch loop
  if (madvise(base_, pool_size, MADV_POPULATE_WRITE) != 0) {
    for (uint64_t off = 0; off < pool_size; off += 4096) base_[off] = 0;
  }
}

Pool::~Pool() {
  if (base_) munmap(base_, pool_size_);
  unlink(path_.c_str());
}

int64_t Pool::find_run(uint64_t k) {
  // scan from the rover with wraparound; bitmap word = 64 blocks
  auto bit_free = [&](uint64_t i) {
    return (bitmap_[i >> 6] & (1ULL << (i & 63))) == 0;
  };
  uint64_t start = rover_ % total_blocks_;
  for (int pass = 0; pass < 2; pass++) {
    uint64_t lo = pass == 0 ? start : 0;
    uint64_t hi = pass == 0 ? total_blocks_ : start;
    uint64_t run = 0, run_start = 0;
    for (uint64_t i = lo; i < hi; i++) {
      // skip full words fast when starting a fresh run
      if (run == 0 && (i & 63) == 0 && bitmap_[i >> 6] == ~0ULL) {
        i += 63;
        continue;
      }
      if (bit_free(i)) {
        if (run == 0) run_start = i;
        if (++run == k) return static_cast<int64_t>(run_start);
      } else {
        run = 0;
      }
    }
  }
  return -1;
}

int64_t Pool::allocate(uint64_t size) {
  uint64_t k = round_up(size, block_size_) / block_size_;
  if (k == 0 || k > total_blocks_ - allocated_blocks_) return -1;
  int64_t idx = find_run(k);
  if (idx < 0) return -1;
  for (uint64_t i = idx; i < idx + k; i++) bitmap_[i >> 6] |= 1ULL << (i & 63);
  allocated_blocks_ += k;
  rover_ = (idx + k) % total_blocks_;
  return idx * static_cast<int64_t>(block_size_);
}

void Pool::deallocate(uint64_t offset, uint64_t size) {
  uint64_t k = round_up(size, block_size_) / block_size_;
  uint64_t idx = offset / block_size_;
  for (uint64_t i = idx; i < idx + k; i++) bitmap_[i >> 6] &= ~(1ULL << (i & 63));
  allocated_blocks_ -= k;
}

MM::MM(uint64_t pool_size, uint64_t block_size, const std::string& name_prefix)
    : block_size_(block_size), name_prefix_(name_prefix) {
  char buf[256];
  snprintf(buf, sizeof(buf), "%s_p0", name_prefix_.c_str());
  pools_.emplace_back(
      std::make_unique<Pool>(buf, round_up(pool_size, block_size), block_size));
}

Pool* MM::add_pool(uint64_t pool_size) {
  char buf[256];
  snprintf(buf, sizeof(buf), "%s_p%zu", name_prefix_.c_str(), pools_.size());
  pools_.emplace_back(
      std::make_unique<Pool>(buf, round_up(pool_size, block_size_), block_size_));
  return pools_.back().get();
}

bool MM::allocate(uint64_t size, size_t n, std::vector<Region>* out) {
  size_t start = out->size();
  for (size_t i = 0; i < n; i++) {
    bool placed = false;
    for (uint32_t pi = 0; pi < pools_.size(); pi++) {
      int64_t off = pools_[pi]->allocate(size);
      if (off >= 0) {
        out->push_back({pi, static_cast<uint64_t>(off)});
        placed = true;
        break;
      }
    }
    if (!placed) {  // roll back: all-or-nothing
      need_extend = true;
      for (size_t j = start; j < out->size(); j++) {
        pools_[(*out)[j].pool_idx]->deallocate((*out)[j].offset, size);
      }
      out->resize(start);
      return false;
    }
  }
  return true;
}

void MM::deallocate(uint32_t pool_idx, uint64_t offset, uint64_t size) {
  pools_[pool_idx]->deallocate(offset, size);
}

double MM::usage() const {
  uint64_t total = 0, used = 0;
  for (const auto& p : pools_) {
    total += p->total_blocks();
    used += p->allocated_blocks();
  }
  return total ? static_cast<double>(used) / total : 0.0;
}

}  // namespace istpu
