#include "mempool.h"

#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdlib.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <stdexcept>

#ifndef MADV_POPULATE_WRITE
#define MADV_POPULATE_WRITE 23  // linux >= 5.14
#endif

namespace istpu {

static uint64_t round_up(uint64_t x, uint64_t align) {
  return (x + align - 1) / align * align;
}

Pool::Pool(const std::string& name, uint64_t pool_size, uint64_t block_size)
    : name_(name),
      path_("/dev/shm/" + name),
      pool_size_(pool_size),
      block_size_(block_size),
      total_blocks_(pool_size / block_size),
      bitmap_((pool_size / block_size + 63) / 64, 0) {
  if (pool_size % block_size != 0) throw std::invalid_argument("pool_size % block_size");
  int fd = open(path_.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) throw std::runtime_error("shm open failed: " + path_);
  if (ftruncate(fd, static_cast<off_t>(pool_size)) != 0) {
    close(fd);
    unlink(path_.c_str());
    throw std::runtime_error("ftruncate failed: " + path_);
  }
  void* p = mmap(nullptr, pool_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (p == MAP_FAILED) {
    unlink(path_.c_str());
    throw std::runtime_error("mmap failed: " + path_);
  }
  base_ = static_cast<uint8_t*>(p);
  // Pre-fault in the background (the ibv_reg_mr-pin analog) so the server
  // can bind/listen immediately; a 16 GiB pool takes minutes to fault in.
  if (getenv("ISTPU_NO_PREFAULT")) {
    prefault_done_ = true;
  } else {
    prefault_thread_ = std::thread([this] { prefault_bg(); });
  }
}

void Pool::prefault_bg() {
  constexpr uint64_t kChunk = 1ULL << 28;  // 256 MB so teardown never waits long
  for (uint64_t off = 0; off < pool_size_ && !closing_; off += kChunk) {
    uint64_t n = std::min(kChunk, pool_size_ - off);
    if (madvise(base_ + off, n, MADV_POPULATE_WRITE) != 0) {
      // pre-5.14 kernel: read-touch.  Never zero-fill off-thread -- the
      // data path may already be writing live blocks into these pages.
      for (uint64_t o2 = off; o2 < off + n && !closing_; o2 += 4096) {
        (void)*static_cast<volatile uint8_t*>(base_ + o2);
      }
    }
  }
  prefault_done_ = true;
}

Pool::~Pool() {
  closing_ = true;
  if (prefault_thread_.joinable()) prefault_thread_.join();
  if (base_) munmap(base_, pool_size_);
  unlink(path_.c_str());
}

int64_t Pool::find_run(uint64_t k) {
  // scan from the rover with wraparound; bitmap word = 64 blocks
  auto bit_free = [&](uint64_t i) {
    return (bitmap_[i >> 6] & (1ULL << (i & 63))) == 0;
  };
  uint64_t start = rover_ % total_blocks_;
  for (int pass = 0; pass < 2; pass++) {
    uint64_t lo = pass == 0 ? start : 0;
    // pass 1 runs past `start` by k-1 blocks so a free run straddling the
    // rover position (begins before it, ends after) is still found
    uint64_t hi = pass == 0 ? total_blocks_
                            : std::min(start + k - 1, total_blocks_);
    uint64_t run = 0, run_start = 0;
    for (uint64_t i = lo; i < hi; i++) {
      // skip full words fast when starting a fresh run
      if (run == 0 && (i & 63) == 0 && bitmap_[i >> 6] == ~0ULL) {
        i += 63;
        continue;
      }
      if (bit_free(i)) {
        if (run == 0) run_start = i;
        if (++run == k) return static_cast<int64_t>(run_start);
      } else {
        run = 0;
      }
    }
  }
  return -1;
}

int64_t Pool::allocate(uint64_t size) {
  uint64_t k = round_up(size, block_size_) / block_size_;
  if (k == 0 || k > total_blocks_ - allocated_blocks_) return -1;
  int64_t idx = find_run(k);
  if (idx < 0) return -1;
  for (uint64_t i = idx; i < idx + k; i++) bitmap_[i >> 6] |= 1ULL << (i & 63);
  allocated_blocks_ += k;
  rover_ = (idx + k) % total_blocks_;
  return idx * static_cast<int64_t>(block_size_);
}

void Pool::deallocate(uint64_t offset, uint64_t size) {
  uint64_t k = round_up(size, block_size_) / block_size_;
  uint64_t idx = offset / block_size_;
  for (uint64_t i = idx; i < idx + k; i++) bitmap_[i >> 6] &= ~(1ULL << (i & 63));
  allocated_blocks_ -= k;
}

void Pool::reclassify(uint64_t new_block_size) {
  // carved budget never returns to the MM, so an idle class's segment
  // must be reusable by a starved one (mirrors Python Pool.reclassify)
  if (allocated_blocks_ != 0 || pool_size_ < new_block_size) return;
  block_size_ = new_block_size;
  total_blocks_ = pool_size_ / new_block_size;  // floor; tail wasted
  allocated_blocks_ = 0;
  rover_ = 0;
  bitmap_.assign((total_blocks_ + 63) / 64, 0);
}

int sweep_stale_segments() {
  int removed = 0;
  DIR* d = opendir("/dev/shm");
  if (!d) return 0;
  while (dirent* ent = readdir(d)) {
    int pid = 0;
    if (sscanf(ent->d_name, "istpu_%d_", &pid) != 1 || pid <= 0) continue;
    if (pid == getpid()) continue;
    if (kill(pid, 0) == 0 || errno != ESRCH) continue;  // owner alive / EPERM
    std::string path = std::string("/dev/shm/") + ent->d_name;
    if (unlink(path.c_str()) == 0) removed++;
  }
  closedir(d);
  return removed;
}

static uint64_t pow2ceil(uint64_t x) {
  uint64_t p = 1;
  while (p < x) p <<= 1;
  return p;
}

MM::MM(uint64_t pool_size, uint64_t block_size, const std::string& name_prefix,
       Allocator allocator)
    : allocator_(allocator), block_size_(block_size), name_prefix_(name_prefix) {
  sweep_stale_segments();  // reclaim segments of SIGKILL'd servers
  if (allocator_ == Allocator::kSizeClass) {
    budget_ = pool_size;  // pools carve lazily per class
    return;
  }
  char buf[256];
  snprintf(buf, sizeof(buf), "%s_p0", name_prefix_.c_str());
  pools_.emplace_back(
      std::make_unique<Pool>(buf, round_up(pool_size, block_size), block_size));
}

Pool* MM::add_pool(uint64_t pool_size) {
  if (allocator_ == Allocator::kSizeClass) {
    budget_ += pool_size;  // the auto-extend contract grants budget
    return nullptr;
  }
  char buf[256];
  snprintf(buf, sizeof(buf), "%s_p%zu", name_prefix_.c_str(), pools_.size());
  pools_.emplace_back(
      std::make_unique<Pool>(buf, round_up(pool_size, block_size_), block_size_));
  return pools_.back().get();
}

uint64_t MM::class_of(uint64_t size) const {
  return pow2ceil(std::max(size, block_size_));
}

int64_t MM::carve(uint64_t cls) {
  // first try RECLASSIFYING an empty pool of another class (carved
  // budget never returns, so one busy class must not permanently starve
  // the rest), then carve fresh budget: a chunk of budget/kCarveDivisor
  // (at least one block, at most what's left), whole blocks only —
  // mirrors the Python MM._carve.  Returns the pool INDEX: a
  // reclassified pool keeps its original slot.
  for (size_t pi = 0; pi < pools_.size(); pi++) {
    auto& p = pools_[pi];
    if (p->block_size() != cls && p->allocated_blocks() == 0 &&
        p->pool_size() >= cls) {
      p->reclassify(cls);
      return static_cast<int64_t>(pi);
    }
  }
  uint64_t remaining = budget_ - carved_;
  uint64_t want = std::max(budget_ / kCarveDivisor, cls);
  uint64_t take = std::min(want, remaining);
  take -= take % cls;
  if (take < cls) return -1;
  char buf[256];
  snprintf(buf, sizeof(buf), "%s_p%zu", name_prefix_.c_str(), pools_.size());
  pools_.emplace_back(std::make_unique<Pool>(buf, take, cls));
  carved_ += take;
  return static_cast<int64_t>(pools_.size() - 1);
}

bool MM::allocate(uint64_t size, size_t n, std::vector<Region>* out) {
  if (size == 0 || size > kMaxAllocSize) return false;  // wire-controlled
  const bool sized = allocator_ == Allocator::kSizeClass;
  const uint64_t cls = sized ? class_of(size) : 0;
  size_t start = out->size();
  for (size_t i = 0; i < n; i++) {
    bool placed = false;
    for (uint32_t pi = 0; pi < pools_.size(); pi++) {
      if (sized && pools_[pi]->block_size() != cls) continue;
      int64_t off = pools_[pi]->allocate(size);
      if (off >= 0) {
        out->push_back({pi, static_cast<uint64_t>(off)});
        placed = true;
        break;
      }
    }
    if (!placed && sized) {
      int64_t pi = carve(cls);
      if (pi >= 0) {
        // pi is the REAL index (reclassified pools keep their slot);
        // recording pools_.size()-1 here pointed view()/deallocate at
        // the wrong pool's bytes
        int64_t off = pools_[pi]->allocate(size);
        if (off >= 0) {
          out->push_back(
              {static_cast<uint32_t>(pi), static_cast<uint64_t>(off)});
          placed = true;
        }
      }
    }
    if (!placed) {  // roll back: all-or-nothing
      need_extend = true;
      for (size_t j = start; j < out->size(); j++) {
        pools_[(*out)[j].pool_idx]->deallocate((*out)[j].offset, size);
      }
      out->resize(start);
      return false;
    }
  }
  return true;
}

bool MM::allocate_contiguous(uint64_t size, size_t n, std::vector<Region>* out) {
  if (size == 0 || n == 0 || size > kMaxAllocSize || size > kMaxAllocSize / n)
    return false;
  const bool sized = allocator_ == Allocator::kSizeClass;
  const uint64_t cls = sized ? class_of(size) : 0;
  for (uint32_t pi = 0; pi < pools_.size(); pi++) {
    Pool* p = pools_[pi].get();
    if (sized && p->block_size() != cls) continue;
    uint64_t stride = round_up(size, p->block_size());
    int64_t off = p->allocate(stride * n);
    if (off >= 0) {
      for (size_t i = 0; i < n; i++)
        out->push_back({pi, static_cast<uint64_t>(off) + i * stride});
      return true;
    }
  }
  if (sized) {
    // carve (or reclassify) a class pool and retry the run there
    int64_t pi = carve(cls);
    if (pi >= 0) {
      int64_t off = pools_[pi]->allocate(cls * n);
      if (off >= 0) {
        for (size_t i = 0; i < n; i++)
          out->push_back({static_cast<uint32_t>(pi),
                          static_cast<uint64_t>(off) + i * cls});
        return true;
      }
    }
  }
  return false;
}

void MM::deallocate(uint32_t pool_idx, uint64_t offset, uint64_t size) {
  pools_[pool_idx]->deallocate(offset, size);
}

bool MM::eviction_could_satisfy(uint64_t size, size_t n) const {
  if (allocator_ != Allocator::kSizeClass) return false;
  if (size == 0 || size > kMaxAllocSize) return false;
  uint64_t cls = class_of(size);
  uint64_t have = 0, reclassifiable = 0;
  for (const auto& p : pools_) {
    if (p->block_size() == cls)
      have += p->total_blocks();
    else if (p->pool_size() >= cls)
      reclassifiable += p->pool_size() / cls;
  }
  uint64_t budget_blocks = (budget_ - carved_) / cls;
  return n <= have + reclassifiable + budget_blocks;
}

double MM::usage() const {
  uint64_t total = 0, used = 0;
  for (const auto& p : pools_) {
    total += p->pool_size();
    used += p->allocated_blocks() * p->block_size();
  }
  if (allocator_ == Allocator::kSizeClass) {
    // uncarved budget is still capacity: eviction thresholds must not
    // fire while whole classes remain uncarved
    total = std::max(budget_, carved_);
  }
  return total ? static_cast<double>(used) / total : 0.0;
}

}  // namespace istpu
