// Wire protocol - C++ mirror of infinistore_tpu/protocol.py.
//
// Same concept as the reference's packed {magic, op, body_size} header
// (reference: src/protocol.h:35-72) with hand-rolled little-endian bodies
// instead of flatbuffers.  Layouts MUST stay byte-identical to protocol.py:
// the Python client and C++ server interoperate on one socket.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace istpu {

constexpr uint32_t MAGIC = 0x54504B56;  // "VKPT"
constexpr uint8_t VERSION = 1;

#pragma pack(push, 1)
struct Header {
  uint32_t magic;
  uint8_t version;
  uint8_t op;
  uint16_t flags;
  uint32_t body_len;
  uint32_t req_id;
};
struct RespHeader {
  int32_t status;
  uint32_t body_len;
};
struct Desc {
  uint32_t pool_idx;
  uint64_t offset;
  uint64_t size;
};
#pragma pack(pop)

static_assert(sizeof(Header) == 16, "header layout");
static_assert(sizeof(RespHeader) == 8, "resp layout");
static_assert(sizeof(Desc) == 20, "desc layout");

// ops (protocol.py:45-59)
enum Op : uint8_t {
  OP_HELLO = 1,
  OP_PUT_INLINE = 2,
  OP_GET_INLINE = 3,
  OP_ALLOC_PUT = 4,
  OP_COMMIT_PUT = 5,
  OP_GET_DESC = 6,
  OP_EXIST = 7,
  OP_MATCH_LAST_IDX = 8,
  OP_DELETE_KEYS = 9,
  OP_PURGE = 10,
  OP_STATS = 11,
  OP_EVICT = 12,
  OP_PUT_INLINE_BATCH = 13,
  OP_GET_INLINE_BATCH = 14,
  OP_POOLS = 15,
};

// status codes (same numbers as reference src/protocol.h:55-62)
enum Status : int32_t {
  INVALID_REQ = 400,
  FINISH = 200,
  TASK_ACCEPTED = 202,
  INTERNAL_ERROR = 500,
  KEY_NOT_FOUND = 404,
  RETRY = 408,
  SYSTEM_ERROR = 503,
  OUT_OF_MEMORY = 507,
};

inline const char* op_name(uint8_t op) {
  switch (op) {
    case OP_HELLO: return "HELLO";
    case OP_PUT_INLINE: return "PUT_INLINE";
    case OP_GET_INLINE: return "GET_INLINE";
    case OP_ALLOC_PUT: return "ALLOC_PUT";
    case OP_COMMIT_PUT: return "COMMIT_PUT";
    case OP_GET_DESC: return "GET_DESC";
    case OP_EXIST: return "EXIST";
    case OP_MATCH_LAST_IDX: return "MATCH_LAST_IDX";
    case OP_DELETE_KEYS: return "DELETE_KEYS";
    case OP_PURGE: return "PURGE";
    case OP_STATS: return "STATS";
    case OP_EVICT: return "EVICT";
    case OP_PUT_INLINE_BATCH: return "PUT_INLINE_BATCH";
    case OP_GET_INLINE_BATCH: return "GET_INLINE_BATCH";
    case OP_POOLS: return "POOLS";
    default: return "UNKNOWN";
  }
}

// ---- body readers/writers (bounds-checked cursor) ----

class Reader {
 public:
  Reader(const uint8_t* p, size_t n) : p_(p), n_(n) {}
  bool ok() const { return ok_; }
  size_t remaining() const { return n_ - off_; }

  template <typename T>
  T get() {
    T v{};
    if (off_ + sizeof(T) > n_) { ok_ = false; return v; }
    std::memcpy(&v, p_ + off_, sizeof(T));
    off_ += sizeof(T);
    return v;
  }

  bool get_bytes(std::string* out, size_t len) {
    if (off_ + len > n_) { ok_ = false; return false; }
    out->assign(reinterpret_cast<const char*>(p_ + off_), len);
    off_ += len;
    return true;
  }

  // keys: n u32 | n x { len u16 | bytes }  (protocol.py pack_keys)
  bool get_keys(std::vector<std::string>* keys) {
    uint32_t n = get<uint32_t>();
    if (!ok_) return false;
    // n is untrusted wire data: each key needs >= 2 bytes (its u16 length),
    // so any n beyond remaining()/2 is malformed -- reject before reserve()
    // can attempt a multi-GB allocation.
    if (n > remaining() / 2) { ok_ = false; return false; }
    keys->reserve(n);
    for (uint32_t i = 0; i < n; i++) {
      uint16_t klen = get<uint16_t>();
      std::string k;
      if (!ok_ || !get_bytes(&k, klen)) return false;
      keys->push_back(std::move(k));
    }
    return true;
  }

 private:
  const uint8_t* p_;
  size_t n_;
  size_t off_ = 0;
  bool ok_ = true;
};

class Writer {
 public:
  explicit Writer(std::string* out) : out_(out) {}

  template <typename T>
  void put(T v) {
    out_->append(reinterpret_cast<const char*>(&v), sizeof(T));
  }
  void put_bytes(const void* p, size_t n) {
    out_->append(reinterpret_cast<const char*>(p), n);
  }
  void put_keys(const std::vector<std::string>& keys) {
    put<uint32_t>(static_cast<uint32_t>(keys.size()));
    for (const auto& k : keys) {
      put<uint16_t>(static_cast<uint16_t>(k.size()));
      put_bytes(k.data(), k.size());
    }
  }

 private:
  std::string* out_;
};

}  // namespace istpu
